"""PR-14 observability plane: request-scoped tracing (W3C traceparent,
tail sampling, phase spans + histogram exemplars), the crash flight
recorder, and the SLO burn-rate engine — plus the multi-process
Chrome-trace validator satellite.

The acceptance test (`TestRequestTracingE2E`) drives a real HTTP
`/score` with a caller-supplied ``traceparent`` and asserts ONE trace
containing queue-wait, assembly (with a nonzero ``parse`` child), pad,
and device-dispatch spans parented under the request, the same trace id
echoed in the response headers, and that id attached as an exemplar on
the latency-histogram bucket the request landed in.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.obs import flight as flight_mod
from transmogrifai_tpu.obs.export import (
    chrome_trace, merge_chrome_traces, validate_chrome_trace)
from transmogrifai_tpu.obs.flight import FlightRecorder
from transmogrifai_tpu.obs.metrics import MetricsRegistry
from transmogrifai_tpu.obs.slo import (
    SLO, SLOEngine, SLOParams, availability_source, latency_source,
    staleness_source)
from transmogrifai_tpu.obs.trace import (
    TRACER, RequestTrace, Span, TailSampler, TraceContext, TracingParams,
    format_traceparent, now_s, parse_traceparent)
from transmogrifai_tpu.ops.numeric import RealVectorizer
from transmogrifai_tpu.serving.http import serve
from transmogrifai_tpu.serving.service import ScoringService, ServingConfig
from transmogrifai_tpu.workflow import Workflow

D = 3
ROW = {f"x{j}": 0.2 * (j + 1) for j in range(D)}


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    rng = np.random.default_rng(17)
    n = 160
    X = rng.normal(size=(n, D))
    beta = rng.normal(size=D)
    ds = Dataset({**{f"x{j}": X[:, j] for j in range(D)},
                  "y": (X @ beta > 0).astype(np.float64)},
                 {**{f"x{j}": t.Real for j in range(D)},
                  "y": t.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = RealVectorizer(track_nulls=False).set_input(*preds).get_output()
    pred = OpLogisticRegression(max_iter=40).set_input(
        label, vec).get_output()
    out = str(tmp_path_factory.mktemp("obsplane") / "model")
    Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train().save(out)
    return out


@pytest.fixture(scope="module")
def svc(model_dir):
    service = ScoringService.from_path(
        model_dir, config=ServingConfig(max_batch=8, batch_wait_ms=1.0))
    service.start()
    yield service
    service.stop()


# --------------------------------------------------------------------- #
# W3C traceparent                                                       #
# --------------------------------------------------------------------- #

class TestTraceparent:
    def test_roundtrip(self):
        tid = "4bf92f3577b34da6a3ce929d0e0e4736"
        hdr = format_traceparent(tid, 0x1234, sampled=True)
        parsed = parse_traceparent(hdr)
        assert parsed == (tid, "0000000000001234", True)

    def test_unsampled_flag(self):
        hdr = format_traceparent("ab" * 16, 1, sampled=False)
        assert parse_traceparent(hdr)[2] is False

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-00f067aa0ba902b7-01",
        "00-" + "0" * 32 + "-00f067aa0ba902b7-01",   # all-zero trace id
        "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero parent
        "ff-" + "ab" * 16 + "-00f067aa0ba902b7-01",  # invalid version
    ])
    def test_malformed_rejected(self, bad):
        assert parse_traceparent(bad) is None

    def test_short_internal_id_left_padded(self):
        hdr = format_traceparent("abc123def456", 7)
        assert parse_traceparent(hdr) is not None

    def test_context_from_header_carries_sampled(self):
        ctx = TraceContext.from_traceparent(
            format_traceparent("cd" * 16, 9, sampled=True))
        assert ctx.trace_id == "cd" * 16 and ctx.sampled
        rt = RequestTrace(ctx=ctx)
        assert rt.forced and rt.trace_id == "cd" * 16
        assert rt.root.attributes["parent_traceparent"] \
            == "0000000000000009"


# --------------------------------------------------------------------- #
# tail sampler                                                          #
# --------------------------------------------------------------------- #

class TestTailSampler:
    def test_errors_and_forced_always_kept(self):
        s = TailSampler(TracingParams(head_sample_every=1000))
        assert s.decide(0.001, error=True) == (True, "error")
        assert s.decide(0.001, forced=True) == (True, "forced")

    def test_head_sampling_cadence(self):
        s = TailSampler(TracingParams(head_sample_every=8,
                                      min_latency_samples=10_000))
        kept = [s.decide(0.001)[0] for _ in range(32)]
        assert sum(kept) == 4  # 1 in 8
        assert s.dropped == 28

    def test_slow_tail_kept_after_warmup(self):
        s = TailSampler(TracingParams(head_sample_every=10_000,
                                      slow_quantile=0.9,
                                      min_latency_samples=50))
        for _ in range(100):
            s.decide(0.001)
        keep, reason = s.decide(1.0)  # far past the rolling q90
        assert keep and reason == "slow"

    def test_observe_collects_only_kept(self):
        from transmogrifai_tpu.obs.trace import Tracer
        tracer = Tracer()
        s = TailSampler(TracingParams(head_sample_every=1000,
                                      min_latency_samples=10_000))
        rt_drop = RequestTrace()
        # first decision is the head sample; burn it so the next drops
        s.decide(0.001)
        assert not s.observe(rt_drop, 0.001, tracer=tracer)
        rt_keep = RequestTrace()
        rt_keep.finish("boom")
        assert s.observe(rt_keep, 0.001, error=True, tracer=tracer)
        names = [sp.trace_id for sp in tracer.spans()]
        assert rt_keep.trace_id in names
        assert rt_drop.trace_id not in names

    def test_sampler_counters_land_in_registry(self):
        reg = MetricsRegistry()
        s = TailSampler(TracingParams(head_sample_every=4,
                                      min_latency_samples=10_000),
                        registry=reg)
        for _ in range(8):
            s.decide(0.001)
        j = reg.to_json()
        kept = sum(e["value"] for e in
                   j["serving_trace_kept_total"]["series"])
        dropped = j["serving_trace_dropped_total"]["series"][0]["value"]
        assert kept == 2 and dropped == 6

    def test_params_validation(self):
        with pytest.raises(ValueError):
            TracingParams(slow_quantile=1.5)
        with pytest.raises(ValueError):
            TracingParams(head_sample_every=0)


# --------------------------------------------------------------------- #
# request-trace span buffer                                             #
# --------------------------------------------------------------------- #

class TestRequestTrace:
    def test_backdated_children_and_phase_durations(self):
        rt = RequestTrace(rows=3)
        t0 = now_s()
        rt.child_at("serving:queue_wait", t0 - 0.010, t0 - 0.004)
        rt.child_at("serving:device_dispatch", t0 - 0.004, t0 - 0.001,
                    bucket=4)
        with rt.child("serving:demux"):
            pass
        rt.finish()
        phases = rt.phase_durations()
        assert phases["queue_wait"] == pytest.approx(0.006, abs=1e-4)
        assert phases["device_dispatch"] == pytest.approx(0.003, abs=1e-4)
        assert "demux" in phases
        assert all(sp.parent_id == rt.root.span_id
                   for sp in rt.spans[1:])

    def test_finish_idempotent_and_error(self):
        rt = RequestTrace()
        rt.finish("deadline_exceeded")
        end = rt.root.end_s
        rt.finish()  # second finish is a no-op
        assert rt.root.end_s == end
        assert rt.root.error == "deadline_exceeded"


# --------------------------------------------------------------------- #
# flight recorder                                                       #
# --------------------------------------------------------------------- #

class TestFlightRecorder:
    def _span(self, name, error=None, parent=None):
        sp = Span(name, category="serving", parent=parent)
        sp.error = error
        sp.end()
        return sp

    def test_ring_bounded_and_drops_counted(self):
        rec = FlightRecorder(capacity=8)
        rec.enabled = True
        for i in range(20):
            rec.note_event("tick", {"i": i})
        assert len(rec.snapshot()) == 8
        assert rec.records_seen == 20

    def test_dump_is_valid_chrome_trace(self, tmp_path):
        rec = FlightRecorder(capacity=64, dump_dir=str(tmp_path),
                             min_interval_s=0.0)
        rec.enabled = True
        parent = self._span("serving:batch")
        rec.note_span(parent)
        child = self._span("serving:device_dispatch", error="boom",
                           parent=parent)
        rec.note_span(child)
        rec.note_event("breaker_open", {"member": "a"})
        rec.note_metric("queue_depth", 3.0)
        path = rec.dump("unit")
        assert path is not None and path.endswith("unit")
        with open(f"{path}/trace.json") as fh:
            trace = json.load(fh)
        assert validate_chrome_trace(trace) == []
        names = [ev["name"] for ev in trace["traceEvents"]]
        assert "serving:device_dispatch" in names
        assert "breaker_open" in names and "queue_depth" in names
        lines = open(f"{path}/events.jsonl").read().splitlines()
        assert len(lines) == 4
        meta = json.load(open(f"{path}/meta.json"))
        assert meta["reason"] == "unit" and meta["records"] == 4

    def test_orphaned_parent_detached(self, tmp_path):
        rec = FlightRecorder(dump_dir=str(tmp_path), min_interval_s=0.0)
        rec.enabled = True
        never_finished = Span("open:root")
        rec.note_span(self._span("child", parent=never_finished))
        path = rec.dump("orphan")
        with open(f"{path}/trace.json") as fh:
            trace = json.load(fh)
        assert validate_chrome_trace(trace) == []
        ev = next(e for e in trace["traceEvents"]
                  if e.get("name") == "child")
        assert ev["args"]["parent_id"] is None
        assert ev["args"]["orphaned_parent"] == never_finished.span_id

    def test_debounce_and_force(self, tmp_path):
        rec = FlightRecorder(dump_dir=str(tmp_path), min_interval_s=60.0)
        rec.enabled = True
        rec.note_event("x", {})
        assert rec.dump("first") is not None
        assert rec.dump("second") is None          # debounced
        assert rec.dump("third", force=True) is not None
        assert len(rec.dumps) == 2

    def test_reset_clears_debounce_anchor_and_rearms(self, tmp_path):
        # regression (concurrency audit C001): reset() used to null
        # _last_dump_s bare while dump() reads it twice under
        # _dump_lock (None-check, then the subtraction) — a
        # cross-thread reset landing between the reads crashed the
        # dump path. reset() now takes the lock for that write.
        rec = FlightRecorder(dump_dir=str(tmp_path), min_interval_s=60.0)
        rec.enabled = True
        rec.note_event("x", {})
        assert rec.dump("first") is not None
        assert rec.dump("second") is None        # debounced
        rec.reset()
        rec.note_event("y", {})
        assert rec.dump("after-reset") is not None   # debounce re-armed
        assert rec.records_seen == 1
        assert len(rec.dumps) == 1 and rec.dump_failures == 0

    def test_reset_serializes_with_dump_lock(self, tmp_path):
        import threading
        rec = FlightRecorder(dump_dir=str(tmp_path), min_interval_s=60.0)
        rec.enabled = True
        holding = threading.Event()
        release = threading.Event()
        done = threading.Event()

        def holder():
            with rec._dump_lock:
                holding.set()
                release.wait(5.0)

        t = threading.Thread(target=holder, name="dump-holder")
        t.start()
        assert holding.wait(5.0)
        r = threading.Thread(target=lambda: (rec.reset(), done.set()),
                             name="resetter")
        r.start()
        # reset must queue behind the dump lock, not race past it
        assert not done.wait(0.2)
        release.set()
        assert done.wait(5.0)
        t.join()
        r.join()

    def test_disabled_recorder_is_inert(self, tmp_path):
        rec = FlightRecorder(dump_dir=str(tmp_path))
        rec.note_event("x", {})
        assert rec.snapshot() == []
        assert rec.dump("nope") is None

    def test_record_event_feeds_process_recorder(self, tmp_path):
        from transmogrifai_tpu.obs.export import record_event
        rec = flight_mod.get_recorder()
        was = rec.enabled
        rec.enabled = True
        try:
            before = rec.records_seen
            record_event("unit_test_event", foo=1)
            assert rec.records_seen == before + 1
        finally:
            rec.enabled = was


# --------------------------------------------------------------------- #
# SLO burn-rate engine                                                  #
# --------------------------------------------------------------------- #

class TestSLOEngine:
    def _engine(self, reg=None, objective=0.99):
        params = SLOParams(
            slos=[{"name": "avail", "kind": "availability",
                   "objective": objective}],
            windows=[[60.0, 10.0, 2.0, "page"]], eval_period_s=1.0)
        engine = SLOEngine(params, registry=reg)
        state = {"good": 0.0, "total": 0.0}
        engine.set_source("avail",
                          lambda: (state["good"], state["total"]))
        return engine, state

    def test_fires_on_burn_and_clears(self):
        engine, state = self._engine()
        now = 1000.0
        for i in range(12):  # healthy baseline across the long window
            state["good"] += 10
            state["total"] += 10
            engine.evaluate(now=now + i)
        assert engine.firing() == []
        # storm: 50% errors for a few ticks — burn >> 2x of a 1% budget
        for i in range(4):
            state["good"] += 5
            state["total"] += 10
            engine.evaluate(now=now + 12 + i)
        assert engine.firing() == ["avail"]
        st = engine.status(now=now + 16)["slos"]["avail"]
        assert st["state"] == "firing" and st["alerts"] == 1
        # recovery: healthy traffic while the bad samples age out of
        # the 60s window
        for i in range(70):
            state["good"] += 10
            state["total"] += 10
            engine.evaluate(now=now + 16 + i)
        assert engine.firing() == []
        st = engine.status(now=now + 86)["slos"]["avail"]
        assert st["state"] == "ok"

    def test_multiwindow_requires_both(self):
        # a blip that clears before the LONG window accumulates enough
        # budget burn must not page: short window spikes, long stays ok
        params = SLOParams(
            slos=[{"name": "avail", "kind": "availability",
                   "objective": 0.9}],
            windows=[[100.0, 5.0, 5.0, "page"]], eval_period_s=1.0)
        engine = SLOEngine(params)
        state = {"good": 0.0, "total": 0.0}
        engine.set_source("avail",
                          lambda: (state["good"], state["total"]))
        now = 0.0
        for i in range(99):
            state["good"] += 100
            state["total"] += 100
            engine.evaluate(now=now + i)
        # one bad tick: short-window burn explodes (100% of 10 samples
        # over 5s), long window barely moves (10/9910)
        state["total"] += 10
        engine.evaluate(now=now + 99)
        assert engine.firing() == []

    def test_gauges_and_events(self):
        reg = MetricsRegistry()
        engine, state = self._engine(reg=reg)
        carrier = Span("run:test")
        engine.span = carrier
        now = 0.0
        for i in range(12):
            state["good"] += 10
            state["total"] += 10
            engine.evaluate(now=now + i)
        for i in range(4):
            state["total"] += 10
            engine.evaluate(now=now + 12 + i)
        j = reg.to_json()
        assert j["slo_alert_active"]["series"][0]["value"] == 1.0
        burn = j["slo_burn_rate"]["series"][0]
        assert burn["labels"]["slo"] == "avail" and burn["value"] > 2.0
        assert j["slo_budget_remaining"]["series"][0]["value"] < 1.0
        events = [(n, a) for n, _, a in carrier.events
                  if n == "slo_alert"]
        assert events and events[0][1]["state"] == "firing"

    def test_goodput_slo_section(self):
        from transmogrifai_tpu.obs.goodput import build_report
        with TRACER.span("run:slounit", category="run",
                         new_trace=True) as root:
            root.event("slo_alert", slo="avail", state="firing",
                       windows="page:60s")
            root.event("slo_alert", slo="avail", state="resolved",
                       alert_s=2.5)
        report = build_report(root, TRACER.trace_spans(root.trace_id))
        assert report.slo["alerts_fired"] == 1
        assert report.slo["alerts_resolved"] == 1
        assert report.slo["by_slo"]["avail"]["alert_s"] == 2.5
        assert "slo" in report.to_json()

    def test_latency_source_reads_histogram_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "h")
        for v in (0.001, 0.002, 0.2, 0.4):
            h.observe(v)
        good, total = latency_source(reg, "lat_seconds", 0.05)()
        assert (good, total) == (2.0, 4.0)
        # missing family: no traffic, not a crash
        assert latency_source(reg, "nope", 0.05)() == (0.0, 0.0)

    def test_staleness_source_time_slices(self):
        reg = MetricsRegistry()
        g = reg.gauge("continual_staleness_current_seconds", "h")
        src = staleness_source(reg, "continual_staleness_current_seconds",
                               threshold_s=10.0)
        g.set(3.0)
        assert src() == (1.0, 1.0)
        g.set(30.0)
        assert src() == (1.0, 2.0)
        g.set(1.0)
        assert src() == (2.0, 3.0)

    def test_availability_source_sheds_count_against_budget(self):
        reg = MetricsRegistry()
        reg.counter("req_total", "h").inc(90)
        reg.counter("err_total", "h").inc(5)
        reg.counter("shed_total", "h", reason="quota").inc(10)
        good, total = availability_source(
            reg, "req_total", error_families=("err_total",),
            shed_families=("shed_total",))()
        assert (good, total) == (85.0, 100.0)

    def test_availability_successes_mode_sees_total_outage(self):
        # the fleet's requests family ticks on SUCCESS only: during a
        # 100% outage (errors, zero successes) the denominator must
        # come from the error counters or the alert is blind
        reg = MetricsRegistry()
        reg.counter("ok_total", "h", tenant="gold").inc(0)
        reg.counter("err_total", "h", tenant="gold").inc(20)
        good, total = availability_source(
            reg, "ok_total", error_families=("err_total",),
            requests_count="successes", tenant="gold")()
        assert (good, total) == (0.0, 20.0)
        with pytest.raises(ValueError):
            availability_source(reg, "ok_total",
                                requests_count="nonsense")

    def test_latency_source_aggregates_labeled_series(self):
        # a per-tenant-labeled family with NO tenant scope must sum
        # every series, not exact-match an empty label key to nothing
        reg = MetricsRegistry()
        reg.histogram("fleet_lat", "h", tenant="a").observe(0.01)
        reg.histogram("fleet_lat", "h", tenant="a").observe(0.5)
        reg.histogram("fleet_lat", "h", tenant="b").observe(0.02)
        good, total = latency_source(reg, "fleet_lat", 0.05)()
        assert (good, total) == (2.0, 3.0)
        good_a, total_a = latency_source(reg, "fleet_lat", 0.05,
                                         tenant="a")()
        assert (good_a, total_a) == (1.0, 2.0)

    def test_staleness_missing_gauge_is_no_data_not_fresh(self):
        reg = MetricsRegistry()
        src = staleness_source(reg, "continual_staleness_current_seconds",
                               threshold_s=10.0)
        # no gauge published: counters stay frozen -> window rates None
        assert src() == (0.0, 0.0)
        assert src() == (0.0, 0.0)
        reg.gauge("continual_staleness_current_seconds", "h").set(3.0)
        assert src() == (1.0, 1.0)

    def test_slo_validation(self):
        with pytest.raises(ValueError):
            SLO(name="x", kind="nonsense")
        with pytest.raises(ValueError):
            SLO(name="x", objective=1.5)
        with pytest.raises(ValueError):
            SLO(name="x", kind="latency")  # needs threshold_s
        with pytest.raises(ValueError):
            SLOParams(time_scale=0)


# --------------------------------------------------------------------- #
# multi-process Chrome traces (satellite)                               #
# --------------------------------------------------------------------- #

class TestMultiProcessChromeTrace:
    def _spans(self, label):
        with TRACER.span(f"run:{label}", category="run",
                         new_trace=True) as root:
            with TRACER.span("serving:batch"):
                pass
        return TRACER.trace_spans(root.trace_id)

    def test_merged_distinct_pids_validate(self):
        a = chrome_trace(self._spans("procA"), process_name="fleet",
                         pid=100)
        b = chrome_trace(self._spans("procB"), process_name="frontend",
                         pid=200)
        merged = merge_chrome_traces(a, b)
        assert validate_chrome_trace(merged) == []
        pids = {ev["pid"] for ev in merged["traceEvents"]}
        assert pids == {100, 200}
        names = [ev["args"]["name"] for ev in merged["traceEvents"]
                 if ev["ph"] == "M" and ev["name"] == "process_name"]
        assert sorted(names) == ["fleet", "frontend"]

    def test_span_ids_scoped_per_pid(self):
        # the same span id in two pids is legal; a parent reference
        # must resolve within its OWN pid
        ev = lambda pid, sid, parent: {  # noqa: E731
            "ph": "X", "name": "s", "cat": "c", "ts": 10, "dur": 5,
            "pid": pid, "tid": 1,
            "args": {"span_id": sid, "parent_id": parent}}
        meta = lambda pid: {"ph": "M", "name": "process_name",  # noqa: E731
                            "pid": pid, "tid": 0, "args": {"name": "p"}}
        good = {"traceEvents": [meta(1), meta(2), ev(1, 7, None),
                                ev(2, 7, None), ev(2, 8, 7)]}
        assert validate_chrome_trace(good) == []
        # pid 2's child points at a span that only exists in pid 1
        bad = {"traceEvents": [meta(1), meta(2), ev(1, 7, None),
                               ev(2, 8, 7)]}
        assert any("parent 7 not in trace" in p
                   for p in validate_chrome_trace(bad))

    def test_unnamed_pid_with_spans_flagged(self):
        ev = {"ph": "X", "name": "s", "cat": "c", "ts": 10, "dur": 5,
              "pid": 3, "tid": 1, "args": {"span_id": 1,
                                           "parent_id": None}}
        problems = validate_chrome_trace({"traceEvents": [ev]})
        assert any("no process_name metadata" in p for p in problems)


# --------------------------------------------------------------------- #
# acceptance: end-to-end request tracing over HTTP                      #
# --------------------------------------------------------------------- #

class TestRequestTracingE2E:
    CALLER_TID = "feed" * 8

    def _score(self, port, headers=None, rows=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/score",
            data=json.dumps({"rows": rows or [dict(ROW)]}).encode(),
            headers={"Content-Type": "application/json",
                     **(headers or {})})
        return urllib.request.urlopen(req, timeout=30)

    def test_caller_traceparent_single_trace_and_exemplar(self, svc):
        server, _ = serve(svc, block=False)
        try:
            caller = format_traceparent(self.CALLER_TID, 0xBEEF,
                                        sampled=True)
            resp = self._score(server.port,
                               headers={"traceparent": caller})
            body = json.loads(resp.read())

            # 1. the SAME trace id echoed in the response headers
            echo = resp.headers.get("traceparent")
            assert echo is not None
            etid, espan, esampled = parse_traceparent(echo)
            assert etid == self.CALLER_TID and esampled
            assert resp.headers.get("X-Trace-Id") == self.CALLER_TID
            assert body["trace_id"] == self.CALLER_TID

            # 2. ONE trace containing every phase, parented under the
            # request root
            spans = TRACER.trace_spans(self.CALLER_TID)
            by_name = {}
            for sp in spans:
                by_name.setdefault(sp.name, sp)
            root = by_name["serving:request"]
            assert root.parent_id is None
            assert root.attributes["parent_traceparent"] \
                == "000000000000beef"
            # the echoed span id IS the request root
            assert int(espan, 16) == root.span_id
            for phase in ("serving:queue_wait", "serving:assemble",
                          "serving:pad", "serving:device_dispatch",
                          "serving:demux"):
                assert phase in by_name, f"missing {phase}"
            by_id = {sp.span_id: sp for sp in spans}
            for sp in spans:
                if sp is root:
                    continue
                anc = sp
                while anc.parent_id is not None:
                    assert anc.parent_id in by_id, \
                        f"{sp.name}: broken parent chain"
                    anc = by_id[anc.parent_id]
                assert anc is root, f"{sp.name} not under the request"
            # assembly has a NONZERO parse child, parented under it
            parse = by_name["serving:parse"]
            assert parse.parent_id == by_name["serving:assemble"].span_id
            assert parse.duration_s > 0

            # 3. the trace id rides as an exemplar on the latency bucket
            # this request landed in
            hist = svc.registry.find("serving_request_latency_seconds")
            ex = [e for e in hist.exemplars()
                  if e[1] == self.CALLER_TID]
            assert ex, "trace id not attached as a latency exemplar"
            bound, _, value, _ = ex[0]
            assert value <= bound
            # and on the phase family
            ph = svc.registry.find("serving_phase_seconds",
                                   phase="parse")
            assert any(e[1] == self.CALLER_TID for e in ph.exemplars())

            # the exposition renders the exemplar OpenMetrics-style
            txt = svc.registry.to_prometheus()
            assert f'# {{trace_id="{self.CALLER_TID}"}}' in txt
        finally:
            server.shutdown()
            server.server_close()

    def test_no_header_mints_fresh_trace(self, svc):
        server, _ = serve(svc, block=False)
        try:
            resp = self._score(server.port)
            tid = resp.headers.get("X-Trace-Id")
            assert tid and len(tid) == 32
            assert parse_traceparent(resp.headers["traceparent"]) \
                is not None
        finally:
            server.shutdown()
            server.server_close()

    def test_phase_histograms_have_fixed_label_set(self, svc):
        j = svc.registry.to_json()
        phases = {e["labels"]["phase"]
                  for e in j["serving_phase_seconds"]["series"]}
        # pre-bound fixed set (hot path never takes the registry lock);
        # request-derived values never become labels
        assert phases == {"parse", "queue_wait", "assemble", "pad",
                          "device_dispatch", "demux", "admission"}

    def test_error_trace_kept_with_error_span(self, svc):
        before = svc.sampler.kept
        with pytest.raises(Exception):
            svc.score([{"bogus_column": object()}])
        assert svc.sampler.kept == before + 1
        errs = [sp for sp in TRACER.spans()
                if sp.name == "serving:request" and sp.error]
        assert errs

    def test_error_response_echoes_trace_id_over_http(self, svc):
        # a failed request must be as correlatable as a slow one: the
        # error response carries the KEPT error trace's id in headers
        # and body
        server, _ = serve(svc, block=False)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._score(server.port,
                            rows=[{"x0": "not-a-number"}])
            err = ei.value
            tid = err.headers.get("X-Trace-Id")
            assert tid and len(tid) == 32
            assert parse_traceparent(
                err.headers.get("traceparent")) is not None
            body = json.loads(err.read())
            assert body["trace_id"] == tid
            # and that trace actually exists in the ring
            assert any(sp.trace_id == tid and sp.error
                       for sp in TRACER.spans()
                       if sp.name == "serving:request")
        finally:
            server.shutdown()
            server.server_close()

    def test_tracing_disabled_service_still_scores(self, model_dir):
        service = ScoringService.from_path(
            model_dir, config=ServingConfig(
                max_batch=4, tracing={"enabled": False}))
        service.start()
        try:
            res = service.score([dict(ROW)])
            assert res.trace_id is None and res.traceparent is None
            assert service.sampler is None
        finally:
            service.stop()


# --------------------------------------------------------------------- #
# in-process trace propagation (continual-cycle style)                  #
# --------------------------------------------------------------------- #

class TestInProcessPropagation:
    def test_parent_span_context_joins_trace_and_forces_keep(self, svc):
        with TRACER.span("continual:promote", category="continual",
                         new_trace=True) as parent:
            ctx = TraceContext.from_span(parent)
            res = svc.score([dict(ROW)], trace=ctx)
        assert res.trace_id == parent.trace_id
        spans = TRACER.trace_spans(parent.trace_id)
        root = next(sp for sp in spans if sp.name == "serving:request")
        assert root.parent_id == parent.span_id
        assert root.attributes.get("sampled") == "forced"

    def test_live_holdout_rides_cycle_trace(self, svc):
        # the continual loop's live gate: requests parent under the
        # open cycle span via current_span()
        from transmogrifai_tpu.continual.loop import live_holdout_metric
        y = np.ones(2)
        with TRACER.span("continual:cycle", category="continual",
                         new_trace=True) as cycle:
            live_holdout_metric(svc, [dict(ROW), dict(ROW)], y,
                                classification=True)
        spans = TRACER.trace_spans(cycle.trace_id)
        reqs = [sp for sp in spans if sp.name == "serving:request"]
        assert reqs and all(sp.parent_id == cycle.span_id
                            for sp in reqs)


# --------------------------------------------------------------------- #
# SLO + flight over a live service                                      #
# --------------------------------------------------------------------- #

class TestServiceSLOWiring:
    def test_slo_endpoint_and_engine(self, model_dir, tmp_path):
        service = ScoringService.from_path(
            model_dir, config=ServingConfig(
                max_batch=4,
                slo={"slos": [{"name": "avail",
                               "kind": "availability",
                               "objective": 0.99}],
                     "windows": [[2.0, 0.5, 2.0, "page"]],
                     "eval_period_s": 0.05}))
        service.start()
        try:
            assert service.slo_engine is not None
            for _ in range(4):
                service.score([dict(ROW)])
            status = service.slo_engine.evaluate()
            assert "avail" in status["slos"]
            server, _ = serve(service, block=False)
            try:
                got = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/slo",
                    timeout=10).read())
                assert got["slos"]["avail"]["objective"] == 0.99
            finally:
                server.shutdown()
                server.server_close()
        finally:
            service.stop()

    def test_slo_endpoint_404_when_unconfigured(self, svc):
        server, _ = serve(svc, block=False)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/slo", timeout=10)
            assert ei.value.code == 404
        finally:
            server.shutdown()
            server.server_close()

    def test_debug_dump_endpoint(self, svc, tmp_path):
        rec = flight_mod.get_recorder()
        old_dir = rec.dump_dir
        rec.configure(dump_dir=str(tmp_path))
        server, _ = serve(svc, block=False)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/debug/dump", data=b"{}")
            out = json.loads(urllib.request.urlopen(
                req, timeout=30).read())
            assert out["status"] == "dumped"
            with open(f"{out['path']}/trace.json") as fh:
                assert validate_chrome_trace(json.load(fh)) == []
        finally:
            rec.configure(dump_dir=old_dir)
            server.shutdown()
            server.server_close()


# --------------------------------------------------------------------- #
# fleet SLO burn sharing (satellite): replicas publish per-SLO          #
# good/total into a store StateCell; everyone reports fleet-wide burn   #
# --------------------------------------------------------------------- #

class TestFleetSLO:
    def _engine(self, tmp_path, replica):
        params = SLOParams(
            slos=[{"name": "avail", "kind": "availability",
                   "objective": 0.99}],
            windows=[[60.0, 10.0, 2.0, "page"]], eval_period_s=1.0)
        engine = SLOEngine(params)
        state = {"good": 0.0, "total": 0.0}
        engine.set_source("avail",
                          lambda: (state["good"], state["total"]))
        engine.attach_fleet(str(tmp_path), replica)
        return engine, state

    def test_fleet_burn_beside_local(self, tmp_path):
        """Replica A is perfectly healthy locally; replica B burns.
        A's LOCAL burn stays 0 while its FLEET view shows the shared
        burn — the pod-wide signal a single replica cannot see."""
        ea, sa = self._engine(tmp_path, "repA")
        eb, sb = self._engine(tmp_path, "repB")
        now = 1000.0
        for i in range(12):
            sa["good"] += 10
            sa["total"] += 10
            sb["good"] += 5   # 50% errors on B throughout
            sb["total"] += 10
            ea.evaluate(now=now + i)
            eb.evaluate(now=now + i)
        sta = ea.status(now=now + 12)
        slo = sta["slos"]["avail"]
        assert sta["fleet_replica"] == "repA"
        assert slo["windows"]["60s/10s"]["long_burn"] == 0.0  # local: spotless
        fleet = slo["fleet"]
        assert fleet["replicas"] == 2
        # fleet: 60 bad / 240 total = 25% errors vs a 1% budget
        assert fleet["burn"]["60s"] == pytest.approx(25.0, rel=0.05)
        # B sees the same fleet numbers through its own engine
        fb = eb.status(now=now + 12)["slos"]["avail"]["fleet"]
        assert fb["replicas"] == 2
        assert fb["burn"]["60s"] == pytest.approx(25.0, rel=0.05)

    def test_unattached_engine_reports_no_fleet(self):
        params = SLOParams(
            slos=[{"name": "avail", "kind": "availability",
                   "objective": 0.99}],
            windows=[[60.0, 10.0, 2.0, "page"]], eval_period_s=1.0)
        engine = SLOEngine(params)
        engine.set_source("avail", lambda: (10.0, 10.0))
        engine.evaluate(now=1.0)
        st = engine.status(now=2.0)
        assert "fleet_replica" not in st
        assert "fleet" not in st["slos"]["avail"]

    def test_maybe_attach_fleet_env_gate(self, tmp_path, monkeypatch):
        from transmogrifai_tpu.obs.slo import maybe_attach_fleet
        params = SLOParams(
            slos=[{"name": "avail", "kind": "availability",
                   "objective": 0.99}],
            windows=[[60.0, 10.0, 2.0, "page"]], eval_period_s=1.0)
        engine = SLOEngine(params)
        monkeypatch.delenv("TRANSMOGRIFAI_SLO_FLEET_DIR", raising=False)
        assert maybe_attach_fleet(engine) is False
        monkeypatch.setenv("TRANSMOGRIFAI_SLO_FLEET_DIR", str(tmp_path))
        monkeypatch.setenv("TRANSMOGRIFAI_SLO_REPLICA", "rZ")
        assert maybe_attach_fleet(engine) is True
        engine.set_source("avail", lambda: (10.0, 10.0))
        engine.evaluate(now=1.0)
        assert engine.status(now=2.0)["fleet_replica"] == "rZ"
