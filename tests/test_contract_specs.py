"""Contract specs auto-applied to the stage inventory (VERDICT r1 #9).

Every op in ops/ and every model family runs the reusable
transformer/estimator battery (testkit/contract.py): batch vs row-subset
consistency, empty input, save/load round-trip through the registry +
npz packing, and metadata width checks — the OpTransformerSpec /
OpEstimatorSpec parity harness.
"""

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.stages.base import FitContext
from transmogrifai_tpu.testkit import (
    check_estimator_contract, check_transformer_contract)

N = 24
RNG = np.random.default_rng(0)


def _real(seed=1, with_nulls=True):
    r = np.random.default_rng(seed)
    v = r.normal(size=N)
    if with_nulls:
        v = v.copy()
        v[::6] = np.nan
    return lambda: Column.from_values(T.Real, v.copy())


def _realnn(seed=2):
    r = np.random.default_rng(seed)
    v = r.normal(size=N)
    return lambda: Column.from_values(T.RealNN, v.copy())


def _label(k=2, seed=3):
    r = np.random.default_rng(seed)
    v = r.integers(k, size=N).astype(np.float64)
    return lambda: Column.from_values(T.RealNN, v.copy())


def _integral(seed=4):
    r = np.random.default_rng(seed)
    return lambda: Column.from_values(
        T.Integral, r.integers(0, 5, size=N).astype(np.float64))


def _binary(seed=5):
    r = np.random.default_rng(seed)
    vals = [bool(x) if i % 7 else None
            for i, x in enumerate(r.uniform(size=N) > 0.5)]
    return lambda: Column.from_values(T.Binary, list(vals))


def _text(seed=6):
    r = np.random.default_rng(seed)
    words = ["red shoe", "blue hat", "green sock", None, "red hat"]
    vals = [words[i] for i in r.integers(len(words), size=N)]
    return lambda: Column.from_values(T.Text, list(vals))


def _picklist(seed=7):
    r = np.random.default_rng(seed)
    lv = ["a", "b", "c", None]
    vals = [lv[i] for i in r.integers(len(lv), size=N)]
    return lambda: Column.from_values(T.PickList, list(vals))


def _textlist(seed=8):
    r = np.random.default_rng(seed)
    vals = [["tok%d" % x for x in r.integers(6, size=3)] if i % 5 else None
            for i in range(N)]
    return lambda: Column.from_values(T.TextList, list(vals))


def _date(seed=9):
    r = np.random.default_rng(seed)
    base = 1_600_000_000_000
    return lambda: Column.from_values(
        T.DateTime, (base + r.integers(0, 10**9, size=N)).astype(np.float64))


def _geo(seed=10):
    r = np.random.default_rng(seed)
    vals = [[float(r.uniform(-60, 60)), float(r.uniform(-120, 120)), 1.0]
            if i % 6 else None for i in range(N)]
    return lambda: Column.from_values(T.Geolocation, list(vals))


def _vector(d=5, seed=11):
    r = np.random.default_rng(seed)
    X = r.normal(size=(N, d)).astype(np.float32)
    return lambda: Column(T.OPVector, X.copy())


def _counts_vector(d=6, seed=12):
    r = np.random.default_rng(seed)
    X = r.poisson(2.0, size=(N, d)).astype(np.float32)
    return lambda: Column(T.OPVector, X.copy())


def _real_map(seed=13):
    r = np.random.default_rng(seed)
    vals = [{"k1": float(r.normal()), "k2": float(r.normal())}
            if i % 5 else None for i in range(N)]
    return lambda: Column.from_values(T.RealMap, list(vals))


def _text_map(seed=14):
    r = np.random.default_rng(seed)
    lv = ["x", "y", "z"]
    vals = [{"c": lv[int(r.integers(3))], "d": lv[int(r.integers(3))]}
            if i % 5 else None for i in range(N)]
    return lambda: Column.from_values(T.TextMap, list(vals))


def _email(seed=15):
    r = np.random.default_rng(seed)
    vals = [f"u{int(r.integers(5))}@d{int(r.integers(3))}.com"
            if i % 4 else None for i in range(N)]
    return lambda: Column.from_values(T.Email, list(vals))


def _phone(seed=16):
    vals = ["4155552671" if i % 3 else "12" for i in range(N)]
    return lambda: Column.from_values(T.Phone, list(vals))


def _meta_vector(d=3, seed=17):
    from transmogrifai_tpu.data.metadata import (
        NULL_INDICATOR, VectorColumnMetadata, VectorMetadata)
    r = np.random.default_rng(seed)
    X = r.normal(size=(N, d)).astype(np.float32)
    meta = VectorMetadata("v", tuple(
        [VectorColumnMetadata("p", "Real") for _ in range(d - 1)] +
        [VectorColumnMetadata("p", "Real",
                              indicator_value=NULL_INDICATOR)])).with_indices()
    return lambda: Column(T.OPVector, X.copy(), meta=meta)


def _mk(factory, *col_makers, **kw):
    return (factory, lambda: [m() for m in col_makers], kw)


def _transformer_inventory():
    import transmogrifai_tpu.ops as O
    from transmogrifai_tpu.data.metadata import NULL_INDICATOR
    return {
        "TextTokenizer": _mk(lambda: O.TextTokenizer(), _text()),
        "HashingVectorizer": _mk(
            lambda: O.HashingVectorizer(num_features=16), _text()),
        "AliasTransformer": _mk(
            lambda: O.AliasTransformer(name="x"), _real()),
        "LambdaMap": _mk(
            lambda: O.LambdaMap(fn=lambda v: None if v is None else v * 2,
                                out_type=T.Real), _real()),
        "FilterTransformer": _mk(
            lambda: O.FilterTransformer(predicate=lambda v: v > 0), _real()),
        "ExistsTransformer": _mk(
            lambda: O.ExistsTransformer(predicate=lambda v: v > 0), _real()),
        "ReplaceTransformer": _mk(
            lambda: O.ReplaceTransformer(old="a", new="z"), _picklist()),
        "ToOccurTransformer": _mk(lambda: O.ToOccurTransformer(), _text()),
        "SubstringTransformer": _mk(
            lambda: O.SubstringTransformer(), _text(), _text(seed=61)),
        "TextLenTransformer": _mk(lambda: O.TextLenTransformer(), _text()),
        "JaccardSimilarity": _mk(
            lambda: O.JaccardSimilarity(),
            lambda: Column.from_values(
                T.MultiPickList, [{"a", "b"} if i % 2 else None
                                  for i in range(N)]),
            lambda: Column.from_values(
                T.MultiPickList, [{"b", "c"} for _ in range(N)])),
        "NGramSimilarity": _mk(
            lambda: O.NGramSimilarity(), _text(), _text(seed=62)),
        "TimePeriodTransformer": _mk(
            lambda: O.TimePeriodTransformer(period="DayOfWeek"), _date()),
        "ValidEmailTransformer": _mk(
            lambda: O.ValidEmailTransformer(), _email()),
        "EmailDomainTransformer": _mk(
            lambda: O.EmailDomainTransformer(), _email()),
        "EmailToPickListMapTransformer": _mk(
            lambda: O.EmailToPickListMapTransformer(), _email()),
        "UrlIsValidTransformer": _mk(
            lambda: O.UrlIsValidTransformer(),
            lambda: Column.from_values(
                T.URL, [f"https://d{i % 3}.com/x" if i % 4 else None
                        for i in range(N)])),
        "PhoneIsValidTransformer": _mk(
            lambda: O.PhoneIsValidTransformer(), _phone()),
        "PhoneIsValidWithRegionTransformer": _mk(
            lambda: O.PhoneIsValidWithRegionTransformer(), _phone(),
            lambda: Column.from_values(
                T.Text, [["US", "Germany", None][i % 3] for i in range(N)])),
        "PhoneParseTransformer": _mk(
            lambda: O.PhoneParseTransformer(), _phone()),
        "PhoneParseWithRegionTransformer": _mk(
            lambda: O.PhoneParseWithRegionTransformer(), _phone(),
            lambda: Column.from_values(
                T.Text, [["GB", "France", None][i % 3] for i in range(N)])),
        "PhoneMapIsValidTransformer": _mk(
            lambda: O.PhoneMapIsValidTransformer(),
            lambda: Column.from_values(
                T.PhoneMap, [{"h": "4155552671", "w": "12"} if i % 2 else None
                             for i in range(N)])),
        "PhoneVectorizer": _mk(lambda: O.PhoneVectorizer(), _phone()),
        "MimeTypeDetector": _mk(
            lambda: O.MimeTypeDetector(),
            lambda: Column.from_values(
                T.Base64, ["JVBERi0xLjc=" if i % 2 else None
                           for i in range(N)])),
        "LangDetector": _mk(lambda: O.LangDetector(), _text()),
        "HumanNameDetector": _mk(
            lambda: O.HumanNameDetector(),
            lambda: Column.from_values(
                T.Text, ["Mary Jones" if i % 2 else "report q3"
                         for i in range(N)])),
        "NameEntityRecognizer": _mk(
            lambda: O.NameEntityRecognizer(),
            lambda: Column.from_values(
                T.Text, ["Talk to James Smith" if i % 2 else None
                         for i in range(N)])),
        "OpStopWordsRemover": _mk(
            lambda: O.OpStopWordsRemover(), _textlist()),
        "OpNGram": _mk(lambda: O.OpNGram(n=2), _textlist()),
        "VectorsCombiner": _mk(
            lambda: O.VectorsCombiner(), _vector(), _vector(d=3, seed=63)),
        "DropIndicesByTransformer": _mk(
            lambda: O.DropIndicesByTransformer(
                predicate=lambda c: c.indicator_value == NULL_INDICATOR),
            _meta_vector(), check_serialization=False),  # needs graph metadata
        "RealNNVectorizer": _mk(lambda: O.RealNNVectorizer(), _realnn()),
        "DateToUnitCircleVectorizer": _mk(
            lambda: O.DateToUnitCircleVectorizer(periods=["HourOfDay"]),
            _date()),
        "NumericBucketizer": _mk(
            lambda: O.NumericBucketizer(splits=[-1.0, 0.0, 1.0]), _real()),
        "ScalerTransformer": _mk(
            lambda: O.ScalerTransformer(scaling_type="linear", slope=2.0,
                                        intercept=1.0), _realnn()),
        "BinaryMathTransformer": _mk(
            lambda: O.BinaryMathTransformer(op="plus"),
            _real(), _real(seed=64)),
        "ScalarMathTransformer": _mk(
            lambda: O.ScalarMathTransformer(op="multiply", scalar=3.0),
            _real()),
        "UnaryMathTransformer": _mk(
            lambda: O.UnaryMathTransformer(op="abs"), _real()),
    }


def _estimator_inventory():
    import transmogrifai_tpu.models as M
    import transmogrifai_tpu.ops as O
    from transmogrifai_tpu.automl.sanity_checker import (
        MinVarianceFilter, SanityChecker)
    return {
        "RealVectorizer": _mk(
            lambda: O.RealVectorizer(), _real(), _real(seed=71)),
        "IntegralVectorizer": _mk(lambda: O.IntegralVectorizer(), _integral()),
        "BinaryVectorizer": _mk(lambda: O.BinaryVectorizer(), _binary()),
        "OneHotVectorizer": _mk(
            lambda: O.OneHotVectorizer(top_k=3, min_support=1), _picklist()),
        "MultiPickListVectorizer": _mk(
            lambda: O.MultiPickListVectorizer(top_k=3, min_support=1),
            lambda: Column.from_values(
                T.MultiPickList,
                [{"a", "b"} if i % 3 else None for i in range(N)])),
        "SmartTextVectorizer": _mk(
            lambda: O.SmartTextVectorizer(max_cardinality=3, min_support=1,
                                          num_features=8), _text()),
        "GeolocationVectorizer": _mk(
            lambda: O.GeolocationVectorizer(), _geo()),
        "OpScalarStandardScaler": _mk(
            lambda: O.OpScalarStandardScaler(), _realnn()),
        "FillMissingWithMean": _mk(lambda: O.FillMissingWithMean(), _real()),
        "PercentileCalibrator": _mk(
            lambda: O.PercentileCalibrator(buckets=10), _realnn()),
        "DecisionTreeNumericBucketizer": _mk(
            lambda: O.DecisionTreeNumericBucketizer(max_depth=2),
            _label(), _real()),
        "OpStringIndexer": _mk(
            lambda: O.OpStringIndexer(handle_invalid="keep"), _picklist()),
        "NumericMapVectorizer": _mk(
            lambda: O.NumericMapVectorizer(), _real_map()),
        "TextMapPivotVectorizer": _mk(
            lambda: O.TextMapPivotVectorizer(top_k=3, min_support=1),
            _text_map()),
        "SmartTextMapVectorizer": _mk(
            lambda: O.SmartTextMapVectorizer(max_cardinality=5, min_support=1,
                                             num_features=8), _text_map()),
        "PhoneMapVectorizer": _mk(
            lambda: O.PhoneMapVectorizer(),
            lambda: Column.from_values(
                T.PhoneMap, [{"h": "4155552671"} if i % 2 else None
                             for i in range(N)])),
        "OpCountVectorizer": _mk(
            lambda: O.OpCountVectorizer(min_df=1.0), _textlist()),
        "OpWord2Vec": _mk(
            lambda: O.OpWord2Vec(vector_size=4, min_count=1, window=2,
                                 num_iter=1), _textlist()),
        "OpLDA": _mk(lambda: O.OpLDA(k=2, max_iter=5), _counts_vector()),
        "SanityChecker": _mk(
            lambda: SanityChecker(max_correlation=2.0), _label(), _vector()),
        "MinVarianceFilter": _mk(lambda: MinVarianceFilter(), _vector()),
        "OpLogisticRegression": _mk(
            lambda: M.OpLogisticRegression(max_iter=5), _label(), _vector()),
        "OpLinearRegression": _mk(
            lambda: M.OpLinearRegression(), _realnn(), _vector()),
        "OpLinearSVC": _mk(
            lambda: M.OpLinearSVC(max_iter=5), _label(), _vector()),
        "OpNaiveBayes": _mk(
            lambda: M.OpNaiveBayes(), _label(), _counts_vector()),
        "OpRandomForestClassifier": _mk(
            lambda: M.OpRandomForestClassifier(n_trees=3, max_depth=2,
                                               max_bins=8),
            _label(), _vector()),
        "OpGBTRegressor": _mk(
            lambda: M.OpGBTRegressor(n_estimators=3, max_depth=2, max_bins=8),
            _realnn(), _vector()),
        "IsotonicRegressionCalibrator": _mk(
            lambda: M.IsotonicRegressionCalibrator(), _realnn(), _realnn()),
    }


@pytest.mark.parametrize("name", sorted(_transformer_inventory()))
def test_transformer_contract(name):
    factory, cols, kw = _transformer_inventory()[name]
    check_transformer_contract(factory, cols, **kw)


@pytest.mark.parametrize("name", sorted(_estimator_inventory()))
def test_estimator_contract(name):
    factory, cols, kw = _estimator_inventory()[name]
    check_estimator_contract(factory, cols, ctx=FitContext(n_rows=N), **kw)
