"""Distributed sweep scheduler (parallel/scheduler.py) + journal shards.

The conftest forces 8 virtual CPU devices (the reference's `local[2]`
trick), so the work-stealing schedule, kill/resume, and steal paths all
exercise for real — and because every virtual device shares one host,
per-worker blocks must reproduce the single-device sweep BIT FOR BIT.
"""

import glob
import json
import os

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
from transmogrifai_tpu.models import OpLinearSVC, OpLogisticRegression
from transmogrifai_tpu.parallel.mesh import make_mesh
from transmogrifai_tpu.parallel.smoke import _cols as _smoke_cols
from transmogrifai_tpu.parallel.smoke import _selector as _smoke_selector
from transmogrifai_tpu.runtime.journal import ShardedSweepJournal
from transmogrifai_tpu.selector import ModelSelector
from transmogrifai_tpu.selector.validators import OpCrossValidation
from transmogrifai_tpu.stages.base import FitContext

N = 240


@pytest.fixture(scope="module")
def cols():
    # shared with the multichip smoke: ONE copy of the synthetic data
    # and of the carefully tuned 3-blocks-of-2 grid — the kill/resume
    # block arithmetic in both files depends on that grid shape
    return _smoke_cols(N)


def _selector(ckpt=None):
    return _smoke_selector(ckpt)


def _fit(selector, cols, mesh=None):
    return selector.fit_model(cols, FitContext(n_rows=N, seed=7, mesh=mesh))


def _rows(model):
    return {(r.model, json.dumps(r.grid, sort_keys=True)): r.fold_metrics
            for r in model.summary.validation_results}


def _need_devices(n=8):
    import jax
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual devices")


# --------------------------------------------------------------------------- #
# journal shards                                                              #
# --------------------------------------------------------------------------- #

def test_sharded_journal_multi_writer_merge(tmp_path):
    base = str(tmp_path / "fam.journal")
    j = ShardedSweepJournal(base, meta={"sig": "abc"})
    j.shard(0).append({"a": 1}, [0.5, 0.6], duration_s=1.0)
    j.shard(3).append({"a": 2}, [0.7, 0.8], duration_s=2.0)
    # merged reads across shards, through any shard's view
    assert j.lookup({"a": 1}) == [0.5, 0.6]
    assert j.shard(3).lookup({"a": 1}) == [0.5, 0.6]
    assert j.duration_of({"a": 2}) == 2.0
    assert len(j) == 2
    assert sorted(os.path.basename(p) for p in glob.glob(base + "-w*")) == \
        ["fam.journal-w0.jsonl", "fam.journal-w3.jsonl"]
    # a fresh instance (resume) discovers and merges every shard
    j2 = ShardedSweepJournal(base, meta={"sig": "abc"})
    assert j2.lookup({"a": 1}) == [0.5, 0.6]
    assert j2.lookup({"a": 2}) == [0.7, 0.8]


def test_sharded_journal_torn_tail_repaired_per_shard(tmp_path):
    base = str(tmp_path / "fam.journal")
    j = ShardedSweepJournal(base, meta={"sig": "s"})
    j.shard(0).append({"a": 1}, [0.5])
    j.shard(1).append({"a": 2}, [0.6])
    # tear shard 1's tail mid-append (kill mid-write)
    with open(base + "-w1.jsonl", "ab") as fh:
        fh.write(b'{"key": "torn')
    j2 = ShardedSweepJournal(base, meta={"sig": "s"})
    assert j2.lookup({"a": 1}) == [0.5]
    assert j2.lookup({"a": 2}) == [0.6]  # intact prefix survives
    # the repaired shard accepts appends again
    j2.shard(1).append({"a": 3}, [0.7])
    j3 = ShardedSweepJournal(base, meta={"sig": "s"})
    assert j3.lookup({"a": 3}) == [0.7]


def test_sharded_journal_merges_legacy_single_file(tmp_path):
    from transmogrifai_tpu.runtime.journal import SweepJournal
    base = str(tmp_path / "fam.journal")
    old = SweepJournal(base, meta={"sig": "s"})
    old.append({"a": 1}, [0.9])
    j = ShardedSweepJournal(base, meta={"sig": "s"})
    assert j.lookup({"a": 1}) == [0.9]  # read-only merge of the old file
    j.shard(0).append({"a": 2}, [0.8])
    # the legacy file was not appended to
    assert SweepJournal(base, meta={"sig": "s"}).lookup({"a": 2}) is None


def test_sharded_journal_meta_mismatch_rotates_shard(tmp_path):
    base = str(tmp_path / "fam.journal")
    ShardedSweepJournal(base, meta={"sig": "old"}).shard(0).append(
        {"a": 1}, [0.5])
    j = ShardedSweepJournal(base, meta={"sig": "new"})
    assert j.lookup({"a": 1}) is None  # stale shard must not resume


# --------------------------------------------------------------------------- #
# static signatures                                                           #
# --------------------------------------------------------------------------- #

def test_static_signature_matches_handler_grouping():
    from transmogrifai_tpu.models import OpRandomForestClassifier
    from transmogrifai_tpu.parallel.sweep import static_signature

    lr = OpLogisticRegression()
    s1 = static_signature(lr, {"reg_param": 0.1, "max_iter": 8})
    s2 = static_signature(lr, {"reg_param": 0.01, "max_iter": 8})
    s3 = static_signature(lr, {"reg_param": 0.1, "max_iter": 4})
    s4 = static_signature(lr, {"reg_param": 0.1, "max_iter": 8,
                               "elastic_net_param": 0.5})
    assert s1 == s2 and s1 != s3 and s1 != s4  # reg traced; iter/enet static

    rf = OpRandomForestClassifier(n_trees=4, max_bins=16)
    # depths 5 and 6 share the 6-bucket; 12 compiles apart
    r5 = static_signature(rf, {"max_depth": 5})
    r6 = static_signature(rf, {"max_depth": 6})
    r12 = static_signature(rf, {"max_depth": 12})
    assert r5 == r6 and r6 != r12

    class Unknown:
        params: dict = {}
    u1 = static_signature(Unknown(), {"p": 1})
    u2 = static_signature(Unknown(), {"p": 2})
    assert u1[0] == "generic" and u1 != u2  # per-config blocks


# --------------------------------------------------------------------------- #
# the schedule                                                                #
# --------------------------------------------------------------------------- #

def test_scheduled_two_family_sweep_bit_identical(cols):
    """Acceptance: a 2-family grid sweep scheduled across an 8-wide
    sweep mesh returns the bit-identical winner (metrics JSON-roundtrip
    exact) to the single-device sweep."""
    _need_devices(8)
    base = _rows(_fit(_selector(), cols))
    mesh = make_mesh(8, sweep=8)
    sched = _rows(_fit(_selector(), cols, mesh=mesh))
    assert set(base) == set(sched)
    for k in base:
        assert json.dumps(base[k]) == json.dumps(sched[k]), k


def test_scheduled_sweep_composes_data_axis(cols):
    """sweep=4 × data=2: each worker lane owns a (1, 2) sub-mesh and
    run_sweep's data path shards its rows — the 2-D composition. Metric
    parity is allclose here (cross-device psum reduction order)."""
    _need_devices(8)
    base = _rows(_fit(_selector(), cols))
    mesh = make_mesh(8, sweep=4)
    sched = _rows(_fit(_selector(), cols, mesh=mesh))
    assert set(base) == set(sched)
    for k in base:
        np.testing.assert_allclose(base[k], sched[k], rtol=2e-4, err_msg=k)


def test_scheduler_kill_resume_reruns_only_inflight_block(cols, tmp_path):
    """Acceptance: killing one worker mid-grid preempts the schedule
    (drain journals every other in-flight block); resuming re-runs only
    the killed worker's in-flight block — asserted from the journal
    shards — and reproduces the bit-identical winner."""
    _need_devices(8)
    from transmogrifai_tpu.runtime.faults import (
        SITE_WORKER_BLOCK, FaultPlan, FaultSpec, InjectedKill)

    mesh = make_mesh(8, sweep=8)
    clean = _fit(_selector(), cols, mesh=mesh)
    ckpt = str(tmp_path / "ckpt")

    def shard_records():
        return sum(max(0, sum(1 for _ in open(p)) - 1)
                   for p in glob.glob(f"{ckpt}/*.journal-w*.jsonl"))

    # kill at the LAST of the 3 block claims: the other two blocks are
    # already in flight and drain to their journals
    plan = FaultPlan([FaultSpec(SITE_WORKER_BLOCK, at=3, kind="kill")])
    with pytest.raises(InjectedKill):
        with plan.active():
            _fit(_selector(ckpt), cols, mesh=mesh)
    journaled = shard_records()
    assert journaled == 4  # 6 configs total - the 2-config in-flight block

    resumed = _fit(_selector(ckpt), cols, mesh=mesh)
    assert shard_records() - journaled == 2  # ONLY the lost block re-ran
    assert resumed.summary.best_grid == clean.summary.best_grid
    b, r = _rows(clean), _rows(resumed)
    for k in b:
        assert json.dumps(b[k]) == json.dumps(r[k]), k


def test_resumed_block_best_accounts_for_prekill_blocks(cols, tmp_path):
    """A family whose grids split into several scheduler blocks: after a
    kill+resume, the records appended by the resumed block carry a
    best-so-far annotation that accounts for the blocks journaled BEFORE
    the kill — run_sweep seeds its tracker from journal.rows(), which
    sees the whole family journal, not just the one re-run block."""
    _need_devices(8)
    from transmogrifai_tpu.runtime.faults import (
        SITE_WORKER_BLOCK, FaultPlan, FaultSpec, InjectedKill)

    mesh = make_mesh(8, sweep=8)
    ckpt = str(tmp_path / "ckpt")

    def one_family(c=None):
        # ONE family, 2 blocks: LPT tie-break runs the (16, False) block
        # first, and more iters converge better, so the family best lives
        # in the FIRST (pre-kill) block — an unseeded tracker on the
        # resumed (max_iter=2) block could not name it
        lr = [{"reg_param": r, "max_iter": it}
              for it in (16, 2) for r in (0.01, 0.1)]
        return ModelSelector(
            models=[(OpLogisticRegression(), lr)],
            validator=OpCrossValidation(n_folds=2, seed=11),
            evaluator=BinaryClassificationEvaluator(),
            checkpoint_dir=c)

    plan = FaultPlan([FaultSpec(SITE_WORKER_BLOCK, at=2, kind="kill")])
    with pytest.raises(InjectedKill):
        with plan.active():
            _fit(one_family(ckpt), cols, mesh=mesh)
    shards = glob.glob(f"{ckpt}/*.journal-w*.jsonl")
    pre = {}
    for p in shards:
        for line in open(p):
            rec = json.loads(line)
            if rec.get("fold_metrics"):
                pre[json.dumps(rec["grid"], sort_keys=True)] = float(
                    np.mean(rec["fold_metrics"]))
    assert len(pre) == 2, f"expected one 2-config block journaled: {pre}"

    _fit(one_family(ckpt), cols, mesh=mesh)
    best_means = []
    for p in glob.glob(f"{ckpt}/*.journal-w*.jsonl"):
        for line in open(p):
            rec = json.loads(line)
            grid = json.dumps(rec.get("grid"), sort_keys=True)
            if rec.get("best") and grid not in pre:  # appended on resume
                best_means.append(float(rec["best"]["mean"]))
    assert best_means, "resume appended no best-annotated records"
    assert max(best_means) >= max(pre.values()), (
        "resumed journal `best` ignores the pre-kill block")


def test_scheduler_steals_block_of_retired_worker(cols):
    """A worker-level error retires one lane; its in-flight block is
    requeued and a survivor steals it — the sweep completes exactly."""
    _need_devices(8)
    from transmogrifai_tpu.obs import goodput as obs_goodput
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.runtime.faults import (
        SITE_WORKER_BLOCK, FaultPlan, FaultSpec)

    mesh = make_mesh(8, sweep=8)
    base = _rows(_fit(_selector(), cols))
    plan = FaultPlan([FaultSpec(SITE_WORKER_BLOCK, at=1, kind="error")])
    with TRACER.span("run:test-steal", category="run",
                     new_trace=True) as root:
        with plan.active():
            stolen = _rows(_fit(_selector(), cols, mesh=mesh))
    for k in base:
        assert json.dumps(base[k]) == json.dumps(stolen[k]), k
    report = obs_goodput.build_report(root, TRACER.trace_spans(root.trace_id))
    assert report.counts.get("workers_retired") == 1
    assert report.mesh.get("requeues", 0) >= 1


def test_scheduler_drops_failing_family_keeps_others(cols):
    """A family whose blocks raise an ordinary Exception fails alone:
    the selector's family-drop policy (OpValidator.scala:344-347) still
    applies under the scheduler."""
    _need_devices(8)
    from transmogrifai_tpu.models import OpNaiveBayes

    mesh = make_mesh(8, sweep=8)
    # NB raises on negative features (Spark parity) — a family-level error
    sel = ModelSelector(
        models=[(OpLogisticRegression(max_iter=8),
                 [{"reg_param": 0.01}, {"reg_param": 0.1}]),
                (OpNaiveBayes(), [{"smoothing": 1.0}])],
        validator=OpCrossValidation(n_folds=2, seed=11),
        evaluator=BinaryClassificationEvaluator())
    model = _fit(sel, cols, mesh=mesh)
    fams = {r.model for r in model.summary.validation_results}
    assert fams == {"OpLogisticRegression"}


def test_scheduler_mesh_utilization_in_goodput(cols):
    _need_devices(8)
    from transmogrifai_tpu.obs import goodput as obs_goodput
    from transmogrifai_tpu.obs.trace import TRACER

    mesh = make_mesh(8, sweep=8)
    with TRACER.span("run:test-mesh", category="run",
                     new_trace=True) as root:
        _fit(_selector(), cols, mesh=mesh)
    report = obs_goodput.build_report(root, TRACER.trace_spans(root.trace_id))
    assert report.mesh, "no mesh rollup in the goodput report"
    assert 0.0 < report.mesh["utilization_frac"] <= 1.0
    assert report.mesh["workers"] == 8
    assert report.mesh["blocks"] == 3
    assert report.mesh["schedules"] == 1
    assert "mesh" in report.to_json()


def test_scheduler_env_optout_uses_sharded_path(cols, monkeypatch):
    """TRANSMOGRIFAI_DISTRIBUTED_SWEEP=0 falls back to the grid-axis
    vmap sharding path (no scheduler spans)."""
    _need_devices(8)
    from transmogrifai_tpu.obs.trace import TRACER

    monkeypatch.setenv("TRANSMOGRIFAI_DISTRIBUTED_SWEEP", "0")
    mesh = make_mesh(8, sweep=8)
    with TRACER.span("run:test-optout", category="run",
                     new_trace=True) as root:
        model = _fit(_selector(), cols, mesh=mesh)
    spans = TRACER.trace_spans(root.trace_id)
    assert not [s for s in spans if s.category == "scheduler"]
    assert np.isfinite([r.mean_metric
                        for r in model.summary.validation_results]).all()


# --------------------------------------------------------------------------- #
# mesh + params plumbing                                                      #
# --------------------------------------------------------------------------- #

def test_multislice_mesh_rejects_nondivisible_device_count():
    from transmogrifai_tpu.parallel.mesh import make_multislice_mesh
    with pytest.raises(ValueError, match="do not divide"):
        make_multislice_mesh(n_slices=3)  # 8 % 3 != 0
    # explicit devices_per_slice still allows a subset
    mesh = make_multislice_mesh(n_slices=3, devices_per_slice=2)
    assert mesh.devices.size == 6
    with pytest.raises(ValueError, match="data_per_slice"):
        make_multislice_mesh(n_slices=2, data_per_slice=3)  # 4 % 3 != 0
    with pytest.raises(ValueError):
        make_multislice_mesh(n_slices=0)


def test_mesh_params_roundtrip_and_build():
    from transmogrifai_tpu.workflow.params import MeshParams, OpParams

    p = OpParams.from_json({"mesh": {"n_devices": 8, "sweep": 4}})
    assert p.mesh == MeshParams(n_devices=8, sweep=4)
    assert OpParams.from_json(p.to_json()).mesh == p.mesh
    mesh = p.mesh.build()
    assert dict(mesh.shape) == {"sweep": 4, "data": 2}
    ms = MeshParams(n_slices=2, data_per_slice=2).build()
    assert dict(ms.shape) == {"sweep": 4, "data": 2}


def test_mesh_params_build_rejects_bad_combinations():
    """A config asking for devices it cannot use fails loudly instead of
    silently training on a subset (or silently ignoring `sweep`)."""
    from transmogrifai_tpu.workflow.params import MeshParams

    with pytest.raises(ValueError, match="does not divide"):
        MeshParams(n_devices=8, n_slices=3).build()
    with pytest.raises(ValueError, match="sweep"):
        MeshParams(n_devices=8, sweep=8, n_slices=2).build()
    with pytest.raises(ValueError, match="data_per_slice"):
        # only the multislice layout reads data_per_slice — on the flat
        # mesh the requested data sharding would be silently dropped
        MeshParams(n_devices=8, data_per_slice=2).build()


def test_single_device_resume_reads_mesh_journal_shards(cols, tmp_path):
    """Resume symmetry: a sweep journaled by MESH workers then resumed
    WITHOUT a mesh (post-preemption fallback) must skip every
    mesh-completed block — and the result stays bit-identical."""
    _need_devices(8)
    from transmogrifai_tpu.runtime.faults import (
        SITE_WORKER_BLOCK, FaultPlan, FaultSpec, InjectedKill)

    mesh = make_mesh(8, sweep=8)
    ckpt = str(tmp_path / "ckpt")
    plan = FaultPlan([FaultSpec(SITE_WORKER_BLOCK, at=3, kind="kill")])
    with pytest.raises(InjectedKill):
        with plan.active():
            _fit(_selector(ckpt), cols, mesh=mesh)

    def records():
        # both shard files AND base .journal files (a family whose only
        # block was the killed one journals its resume re-run there)
        return sum(max(0, sum(1 for _ in open(p)) - 1)
                   for p in glob.glob(f"{ckpt}/*.journal*"))

    journaled = records()
    assert journaled == 4

    resumed = _fit(_selector(ckpt), cols, mesh=None)  # single-device resume
    assert records() - journaled == 2  # only the lost block's configs re-ran
    base = _rows(_fit(_selector(), cols))
    r = _rows(resumed)
    for k in base:
        assert json.dumps(base[k]) == json.dumps(r[k]), k


def test_sharded_journal_glob_metachar_dir(tmp_path):
    """Shard discovery must survive [, ], * in the checkpoint path."""
    d = tmp_path / "ckpt[2026]"
    d.mkdir()
    base = str(d / "fam.journal")
    ShardedSweepJournal(base, meta={"sig": "s"}).shard(2).append(
        {"a": 1}, [0.5])
    j = ShardedSweepJournal(base, meta={"sig": "s"})
    assert j.lookup({"a": 1}) == [0.5]
    assert ShardedSweepJournal.has_shards(base)


def test_scheduled_block_retries_transient_error(cols):
    """A transient error INSIDE a scheduled block (the remote-compile
    RPC drop class) retries via the selector's RetryPolicy instead of
    dropping the family — distribution must not be less fault-tolerant
    than the single-device path."""
    _need_devices(8)
    from transmogrifai_tpu.runtime.faults import (
        SITE_RUN_BLOCK, FaultPlan, FaultSpec)

    mesh = make_mesh(8, sweep=8)
    base = _rows(_fit(_selector(), cols))
    # fires inside run_sweep's block execution on some worker, once
    plan = FaultPlan([FaultSpec(SITE_RUN_BLOCK, at=1, kind="error",
                                transient=True)])
    with plan.active():
        retried = _rows(_fit(_selector(), cols, mesh=mesh))
    assert set(base) == set(retried)  # family survived
    for k in base:
        assert json.dumps(base[k]) == json.dumps(retried[k]), k


def test_sharded_journal_host_qualified_shards_and_refresh(tmp_path):
    """Pod hosts write host-qualified shards (`-w<host>_<lane>`); a
    fresh refresh() on one host discovers shards another host wrote
    AFTER this journal was opened — the cross-host completion log."""
    base = str(tmp_path / "fam.journal")
    mine = ShardedSweepJournal(base, meta={"sig": "s"})
    mine.shard("h0_0").append({"a": 1}, [0.5], duration_s=1.0)
    # another "host" opens the same base later and writes its shard
    theirs = ShardedSweepJournal(base, meta={"sig": "s"})
    theirs.shard("h1_0").append({"a": 2}, [0.7], duration_s=2.0)
    # mine opened before h1's shard existed: refresh pulls it in
    assert mine.refresh() >= 1
    assert mine.lookup({"a": 2}) == [0.7]
    names = sorted(os.path.basename(p) for p in glob.glob(base + "-w*"))
    assert names == ["fam.journal-wh0_0.jsonl", "fam.journal-wh1_0.jsonl"]
    # numeric tokens still parse as ints (legacy single-host shards)
    mine.shard(2).append({"a": 3}, [0.9], duration_s=1.0)
    assert ShardedSweepJournal(base, meta={"sig": "s"}).lookup({"a": 3}) \
        == [0.9]


def test_sharded_journal_illegal_shard_token_rejected(tmp_path):
    base = str(tmp_path / "fam.journal")
    j = ShardedSweepJournal(base, meta={"sig": "s"})
    import pytest
    with pytest.raises(ValueError):
        j.shard("h0/../../etc")


def test_journal_parse_cache_hits_on_unchanged_file(tmp_path):
    """Re-opening an unchanged journal shard must serve rows from the
    (ino, size, mtime) parse cache; an append invalidates the key."""
    from transmogrifai_tpu.runtime import journal as journal_mod
    base = str(tmp_path / "fam.journal")
    j = ShardedSweepJournal(base, meta={"sig": "s"})
    j.shard(0).append({"a": 1}, [0.5], duration_s=1.0)
    # a fresh instance parses the shard once and caches the result...
    j2 = ShardedSweepJournal(base, meta={"sig": "s"})
    assert j2.lookup({"a": 1}) == [0.5]
    path = os.path.abspath(glob.glob(base + "-w*")[0])
    st = os.stat(path)
    with journal_mod._PARSE_CACHE_LOCK:
        stat_key, _state = journal_mod._PARSE_CACHE[path]
    assert stat_key == (st.st_ino, st.st_size, st.st_mtime_ns)
    # append moves size/mtime: the stale key must not be served
    j2.shard(0).append({"a": 2}, [0.7], duration_s=1.0)
    j3 = ShardedSweepJournal(base, meta={"sig": "s"})
    assert j3.lookup({"a": 2}) == [0.7]
