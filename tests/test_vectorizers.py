"""Vectorizer tests (reference: core/src/test/.../feature/*VectorizerTest.scala)."""

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.data import Column
from transmogrifai_tpu.ops import (
    BinaryVectorizer, DateToUnitCircleVectorizer, GeolocationVectorizer,
    HashingVectorizer, IntegralVectorizer, MultiPickListVectorizer,
    OneHotVectorizer, RealNNVectorizer, RealVectorizer, SmartTextVectorizer,
    TextTokenizer, VectorsCombiner)
from transmogrifai_tpu.ops.maps import (
    NumericMapVectorizer, TextMapPivotVectorizer)
from transmogrifai_tpu.ops.text import murmur3_32
from transmogrifai_tpu.stages.base import FeatureGeneratorStage, FitContext


def _raw(name, ftype):
    return FeatureGeneratorStage(name=name, ftype=ftype).get_output()


def _fit_transform(est, feats, cols):
    est.set_input(*feats)
    model = est.fit(cols, FitContext(n_rows=len(cols[0])))
    out = model.transform(cols)
    return model, out


def test_real_vectorizer_mean_impute():
    f = _raw("x", t.Real)
    col = Column.from_values(t.Real, [1.0, None, 3.0])
    model, out = _fit_transform(RealVectorizer(), [f], [col])
    arr = np.asarray(out.data)
    # mean of (1, 3) = 2 imputed; null indicator second column
    np.testing.assert_allclose(arr, [[1, 0], [2, 1], [3, 0]])
    meta = out.meta
    assert meta.size == 2
    assert meta.columns[1].is_null_indicator
    assert meta.columns[0].parent_name == "x"


def test_real_vectorizer_multi_feature():
    fs = [_raw("a", t.Real), _raw("b", t.Real)]
    cols = [Column.from_values(t.Real, [1.0, None]),
            Column.from_values(t.Real, [None, 10.0])]
    model, out = _fit_transform(RealVectorizer(), fs, cols)
    arr = np.asarray(out.data)
    np.testing.assert_allclose(arr, [[1, 0, 10, 1], [1, 1, 10, 0]])


def test_integral_mode_impute():
    f = _raw("n", t.Integral)
    col = Column.from_values(t.Integral, [5, 5, 7, None])
    model, out = _fit_transform(IntegralVectorizer(), [f], [col])
    arr = np.asarray(out.data)
    np.testing.assert_allclose(arr[3], [5, 1])  # mode=5 imputed


def test_binary_vectorizer():
    f = _raw("b", t.Binary)
    col = Column.from_values(t.Binary, [True, False, None])
    model, out = _fit_transform(BinaryVectorizer(), [f], [col])
    np.testing.assert_allclose(np.asarray(out.data), [[1, 0], [0, 0], [0, 1]])


def test_realnn_identity():
    fs = [_raw("a", t.RealNN), _raw("b", t.RealNN)]
    cols = [Column.from_values(t.RealNN, [1.0, 2.0]),
            Column.from_values(t.RealNN, [3.0, 4.0])]
    stage = RealNNVectorizer().set_input(*fs)
    out = stage.transform(cols)
    np.testing.assert_allclose(np.asarray(out.data), [[1, 3], [2, 4]])
    assert out.meta.size == 2


def test_one_hot_top_k():
    f = _raw("c", t.PickList)
    values = ["a"] * 5 + ["b"] * 3 + ["c"] * 1 + [None] * 2
    col = Column.from_values(t.PickList, values)
    model, out = _fit_transform(
        OneHotVectorizer(top_k=2, min_support=2), [f], [col])
    arr = np.asarray(out.data)
    assert arr.shape == (11, 4)  # a, b, OTHER, NULL
    np.testing.assert_allclose(arr[0], [1, 0, 0, 0])   # a
    np.testing.assert_allclose(arr[5], [0, 1, 0, 0])   # b
    np.testing.assert_allclose(arr[8], [0, 0, 1, 0])   # c → OTHER
    np.testing.assert_allclose(arr[9], [0, 0, 0, 1])   # null
    ivals = [m.indicator_value for m in out.meta.columns]
    assert ivals == ["a", "b", "OTHER", "NullIndicatorValue"]


def test_multipicklist_vectorizer():
    f = _raw("tags", t.MultiPickList)
    col = Column.from_values(
        t.MultiPickList, [{"x", "y"}, {"x"}, None, {"z", "x"}])
    model, out = _fit_transform(
        MultiPickListVectorizer(top_k=2, min_support=1), [f], [col])
    arr = np.asarray(out.data)
    assert arr.shape == (4, 4)
    np.testing.assert_allclose(arr[0], [1, 1, 0, 0])  # x, y
    np.testing.assert_allclose(arr[2], [0, 0, 0, 1])  # null
    np.testing.assert_allclose(arr[3], [1, 0, 1, 0])  # x + OTHER(z)


def test_murmur3_deterministic():
    assert murmur3_32(b"") == 0
    assert murmur3_32(b"hello") == murmur3_32(b"hello")
    assert murmur3_32(b"hello") != murmur3_32(b"hellp")
    # known vector: murmur3_32("hello", seed=0) = 0x248bfa47
    assert murmur3_32(b"hello") == 0x248BFA47


def test_hashing_vectorizer():
    f = _raw("words", t.TextList)
    col = Column.from_values(t.TextList, [["cat", "dog"], ["cat"], None])
    stage = HashingVectorizer(num_features=16).set_input(f)
    out = stage.transform([col])
    arr = np.asarray(out.data)
    assert arr.shape == (3, 17)  # 16 hash + 1 null indicator
    assert arr[0].sum() == 2 and arr[1].sum() == 1
    assert arr[2, 16] == 1.0  # null indicator
    # same token → same bucket
    assert np.array_equal(np.nonzero(arr[1, :16])[0],
                          np.intersect1d(np.nonzero(arr[0, :16])[0],
                                         np.nonzero(arr[1, :16])[0]))


def test_tokenizer():
    f = _raw("txt", t.Text)
    col = Column.from_values(t.Text, ["Hello, World! 123", None, ""])
    out = TextTokenizer().set_input(f).transform([col])
    assert out.data[0] == ["hello", "world", "123"]
    assert out.data[1] is None and out.data[2] is None


def test_smart_text_pivot_vs_hash_vs_ignore():
    low = _raw("low", t.Text)    # low cardinality → pivot
    high = _raw("high", t.Text)  # high cardinality, repeating → hash
    ids = _raw("ids", t.Text)    # all-unique → ignore
    n = 60
    low_col = Column.from_values(t.Text, ["a" if i % 2 else "b" for i in range(n)])
    high_col = Column.from_values(
        t.Text, [f"tok{i % 30} blah filler" for i in range(n)])  # 30 distinct / 60
    ids_col = Column.from_values(t.Text, [f"id_{i}" for i in range(n)])
    est = SmartTextVectorizer(max_cardinality=10, top_k=5, min_support=1,
                              num_features=32)
    model, out = _fit_transform(est, [low, high, ids], [low_col, high_col, ids_col])
    assert model.strategies == ["pivot", "hash", "ignore"]
    arr = np.asarray(out.data)
    # widths: pivot = 2 levels + OTHER + NULL = 4; hash = 32 + 1; ignore = 1
    assert arr.shape == (n, 4 + 33 + 1)
    assert out.meta.size == arr.shape[1]


def test_date_unit_circle():
    f = _raw("d", t.Date)
    noon = 12 * 3_600_000
    col = Column.from_values(t.Date, [noon, None])
    stage = DateToUnitCircleVectorizer(periods=("HourOfDay",)).set_input(f)
    out = stage.transform([col])
    arr = np.asarray(out.data)
    assert arr.shape == (2, 2)
    np.testing.assert_allclose(arr[0], [0.0, -1.0], atol=1e-5)  # noon = π
    np.testing.assert_allclose(arr[1], [0.0, 0.0])  # null → origin
    # day-of-week: 1970-01-01 was Thursday → fraction 3/7
    from transmogrifai_tpu.ops.dates import _phase_fraction
    assert _phase_fraction(np.array([0]), "DayOfWeek")[0] == pytest.approx(3 / 7)


def test_geolocation_vectorizer():
    f = _raw("loc", t.Geolocation)
    col = Column.from_values(
        t.Geolocation, [[10.0, 20.0, 1.0], None, [30.0, 40.0, 3.0]])
    model, out = _fit_transform(GeolocationVectorizer(), [f], [col])
    arr = np.asarray(out.data)
    assert arr.shape == (3, 4)
    np.testing.assert_allclose(arr[1], [20, 30, 2, 1])  # mean fill + null flag


def test_numeric_map_vectorizer():
    f = _raw("m", t.RealMap)
    col = Column.from_values(
        t.RealMap, [{"a": 1.0, "b": 2.0}, {"a": 3.0}, None])
    model, out = _fit_transform(NumericMapVectorizer(), [f], [col])
    arr = np.asarray(out.data)
    # keys sorted: a, b → [a, a_null, b, b_null]
    assert arr.shape == (3, 4)
    np.testing.assert_allclose(arr[1], [3, 0, 2, 1])  # b missing → mean 2
    np.testing.assert_allclose(arr[2], [2, 1, 2, 1])
    assert [c.grouping for c in out.meta.columns] == ["a", "a", "b", "b"]


def test_text_map_pivot():
    f = _raw("tm", t.TextMap)
    col = Column.from_values(
        t.TextMap, [{"k": "x"}, {"k": "y"}, {"k": "x"}, None])
    model, out = _fit_transform(
        TextMapPivotVectorizer(top_k=5, min_support=1), [f], [col])
    arr = np.asarray(out.data)
    assert arr.shape == (4, 4)  # x, y, OTHER, NULL
    np.testing.assert_allclose(arr[0], [1, 0, 0, 0])
    np.testing.assert_allclose(arr[3], [0, 0, 0, 1])


def test_vectors_combiner_meta_union():
    fa, fb = _raw("a", t.Real), _raw("c", t.PickList)
    ca = Column.from_values(t.Real, [1.0, None, 2.0, 2.0, None])
    cb = Column.from_values(t.PickList, ["u", "v", "u", None, "u"])
    ra = RealVectorizer().set_input(fa)
    ma = ra.fit([ca], FitContext(5))
    oh = OneHotVectorizer(top_k=3, min_support=1).set_input(fb)
    mb = oh.fit([cb], FitContext(5))
    va, vb = ma.get_output(), mb.get_output()
    comb = VectorsCombiner().set_input(va, vb)
    out = comb.transform([ma.transform([ca]), mb.transform([cb])])
    arr = np.asarray(out.data)
    assert arr.shape == (5, 2 + 4)
    meta = comb.output_meta()
    assert meta.size == 6
    assert [c.index for c in meta.columns] == list(range(6))
    assert meta.columns[0].parent_name == "a"
    assert meta.columns[2].parent_name == "c"


def test_transmogrify_end_to_end_wiring():
    from transmogrifai_tpu.automl import transmogrify
    feats = [
        _raw("age", t.Real), _raw("n", t.Integral), _raw("flag", t.Binary),
        _raw("cat", t.PickList), _raw("txt", t.Text), _raw("d", t.Date),
        _raw("loc", t.Geolocation), _raw("tags", t.MultiPickList),
        _raw("m", t.RealMap),
    ]
    combined = transmogrify(feats)
    assert combined.ftype is t.OPVector
    from transmogrifai_tpu.features import topological_layers
    layers = topological_layers([combined])
    assert len(layers) == 3  # raw → vectorizers → combiner
    assert len(layers[0]) == 9
    assert {s.operation_name for s in layers[2]} == {"VectorsCombiner"}
