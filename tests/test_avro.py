"""Avro container-file codec + reader tests (AvroReaders.scala parity)."""

import io
import json
import os

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.data.avro import (
    _Names, _decoder, _encoder, _read_long, _write_long, avro_ftype,
    dataset_avro_schema, read_container, write_container)
from transmogrifai_tpu.readers import DataReaders


def test_zigzag_varint_roundtrip():
    for n in (0, 1, -1, 63, -64, 64, 1 << 20, -(1 << 20), (1 << 62),
              -(1 << 62)):
        out = io.BytesIO()
        _write_long(out, n)
        assert _read_long(io.BytesIO(out.getvalue())) == n


def test_known_zigzag_bytes():
    # spec examples: 0→00, -1→01, 1→02, -2→03, 2→04
    for n, b in ((0, b"\x00"), (-1, b"\x01"), (1, b"\x02"), (-2, b"\x03"),
                 (2, b"\x04"), (-64, b"\x7f"), (64, b"\x80\x01")):
        out = io.BytesIO()
        _write_long(out, n)
        assert out.getvalue() == b


SCHEMA = {
    "type": "record", "name": "Passenger",
    "fields": [
        {"name": "id", "type": "long"},
        {"name": "name", "type": ["null", "string"], "default": None},
        {"name": "age", "type": ["null", "double"], "default": None},
        {"name": "survived", "type": "boolean"},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "scores", "type": {"type": "map", "values": "double"}},
        {"name": "klass", "type": {"type": "enum", "name": "K",
                                   "symbols": ["a", "b", "c"]}},
    ],
}

RECORDS = [
    {"id": 1, "name": "Ann", "age": 31.5, "survived": True,
     "tags": ["x", "y"], "scores": {"m": 1.0}, "klass": "a"},
    {"id": 2, "name": None, "age": None, "survived": False,
     "tags": [], "scores": {}, "klass": "c"},
    {"id": 3, "name": "Bob", "age": 4.0, "survived": True,
     "tags": ["z"], "scores": {"m": -2.5, "n": 0.0}, "klass": "b"},
]


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_container_roundtrip(tmp_path, codec):
    path = str(tmp_path / f"p_{codec}.avro")
    write_container(path, SCHEMA, RECORDS, codec=codec)
    schema, records = read_container(path)
    assert schema == SCHEMA
    assert records == RECORDS


def test_container_multiblock(tmp_path):
    path = str(tmp_path / "blocks.avro")
    many = [{"id": i, "name": f"r{i}", "age": float(i), "survived": i % 2 == 0,
             "tags": [], "scores": {}, "klass": "a"} for i in range(1000)]
    write_container(path, SCHEMA, many, block_records=64)
    _, records = read_container(path)
    assert records == many


def test_binary_encode_decode_all_types():
    sch = {"type": "record", "name": "R", "fields": [
        {"name": "i", "type": "int"},
        {"name": "f", "type": "float"},
        {"name": "by", "type": "bytes"},
        {"name": "fx", "type": {"type": "fixed", "name": "F", "size": 3}},
        {"name": "u", "type": ["null", "long", "string"]},
    ]}
    rec = {"i": -7, "f": 2.5, "by": b"\x00\x01", "fx": b"abc", "u": "s"}
    names = _Names()
    enc = _encoder(sch, names)
    out = io.BytesIO()
    enc(out, rec)
    dec = _decoder(sch, _Names())
    got = dec(io.BytesIO(out.getvalue()))
    assert got == rec


def test_avro_ftype_mapping():
    names = _Names()
    assert avro_ftype("long", names) is T.Integral
    assert avro_ftype("double", names) is T.Real
    assert avro_ftype("boolean", names) is T.Binary
    assert avro_ftype("string", names) is T.Text
    assert avro_ftype(["null", "string"], names) is T.Text
    assert avro_ftype({"type": "array", "items": "string"}, names) is T.TextList
    assert avro_ftype({"type": "array", "items": "double"}, names) is T.Geolocation
    assert avro_ftype({"type": "map", "values": "double"}, names) is T.TextMap
    assert avro_ftype({"type": "enum", "name": "E", "symbols": ["x"]},
                      names) is T.PickList
    assert avro_ftype({"type": "long", "logicalType": "timestamp-millis"},
                      names) is T.DateTime


def test_dataset_from_avro(tmp_path):
    path = str(tmp_path / "ds.avro")
    write_container(path, SCHEMA, RECORDS)
    ds = Dataset.from_avro(path)
    assert ds.n_rows == 3
    assert ds.schema["id"] is T.Integral
    assert ds.schema["name"] is T.Text
    assert ds.schema["age"] is T.Real
    assert ds.schema["survived"] is T.Binary
    assert ds.schema["tags"] is T.TextList
    assert ds.schema["klass"] is T.PickList
    age = ds.column("age")
    assert age[0] == 31.5 and np.isnan(age[1])
    assert list(ds.column("name")) == ["Ann", None, "Bob"]


def test_dataset_avro_roundtrip(tmp_path):
    ds = Dataset.from_rows(
        [{"x": 1.5, "n": 3, "s": "a", "b": True, "lst": ["p", "q"],
          "mp": {"k": 1.0}},
         {"x": None, "n": None, "s": None, "b": None, "lst": None,
          "mp": None}],
        schema={"x": T.Real, "n": T.Integral, "s": T.Text, "b": T.Binary,
                "lst": T.TextList, "mp": T.RealMap})
    path = str(tmp_path / "rt.avro")
    ds.to_avro(path)
    back = Dataset.from_avro(path, schema=dict(ds.schema))
    assert back.n_rows == 2
    assert back.column("x")[0] == 1.5 and np.isnan(back.column("x")[1])
    assert back.column("n")[0] == 3
    assert back.column("s")[0] == "a" and back.column("s")[1] is None
    assert back.column("lst")[0] == ["p", "q"]
    assert back.column("mp")[0] == {"k": 1.0}


def test_avro_reader_and_stream(tmp_path):
    path = str(tmp_path / "r.avro")
    write_container(path, SCHEMA, RECORDS)
    reader = DataReaders.avro(path, key_column="id")
    ds = reader.read()
    assert ds.n_rows == 3
    from transmogrifai_tpu.readers.readers import KEY_COLUMN
    assert list(ds.column(KEY_COLUMN)) == ["1", "2", "3"]

    sr = DataReaders.stream(avro_path=path, batch_size=2)
    batches = list(sr.stream())
    assert [b.n_rows for b in batches] == [2, 1]
    assert batches[0].schema["age"] is T.Real


def test_workflow_trains_from_avro(tmp_path):
    """End-to-end: avro file → reader → transmogrify → LR train → score."""
    rng = np.random.default_rng(0)
    n = 120
    x = rng.normal(size=n)
    recs = [{"x": float(x[i]),
             "c": ["u", "v"][int(rng.integers(2))],
             "y": float(x[i] + rng.normal(0, 0.3) > 0)} for i in range(n)]
    sch = {"type": "record", "name": "Row", "fields": [
        {"name": "x", "type": ["null", "double"], "default": None},
        {"name": "c", "type": ["null", "string"], "default": None},
        {"name": "y", "type": "double"},
    ]}
    path = str(tmp_path / "train.avro")
    write_container(path, sch, recs)

    from transmogrifai_tpu.automl import transmogrify
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.workflow import Workflow

    ds = Dataset.from_avro(path, schema={"c": T.PickList, "y": T.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = transmogrify(preds)
    pf = OpLogisticRegression(max_iter=15).set_input(label, vec).get_output()
    model = Workflow().set_result_features(pf, label).set_input_dataset(ds).train()
    out = model.score(ds)
    pred = np.asarray(out[pf.name].data["prediction"])
    assert pred.shape == (n,)
    acc = float((pred == np.array([r["y"] for r in recs])).mean())
    assert acc > 0.8


def test_cli_gen_from_avsc_and_avro(tmp_path):
    """`gen` from a bare .avsc schema (reference CLI's schemaFile mode) and
    from an .avro data file."""
    import json as _json
    import sys
    from transmogrifai_tpu.cli import main

    avsc = {"type": "record", "name": "Lead", "fields": [
        {"name": "converted", "type": "boolean"},
        {"name": "revenue", "type": ["null", "double"], "default": None},
        {"name": "source", "type": ["null", "string"], "default": None},
        {"name": "visits", "type": ["null", "long"], "default": None},
    ]}
    avsc_path = tmp_path / "lead.avsc"
    avsc_path.write_text(_json.dumps(avsc))
    out = tmp_path / "lead_app.py"
    rc = main(["gen", "--input", str(avsc_path), "--response", "converted",
               "--output", str(out)])
    assert rc == 0
    code = out.read_text()
    assert "BinaryClassificationModelSelector" in code  # boolean response
    assert 'FeatureBuilder.RealNN("converted")' in code
    assert "DataReaders.avro" in code
    assert 'FeatureBuilder.Integral("visits")' in code

    # data-file mode: problem kind inferred from the actual column values
    path = str(tmp_path / "train.avro")
    write_container(path, SCHEMA, RECORDS)
    out2 = tmp_path / "passenger_app.py"
    rc = main(["gen", "--input", path, "--response", "survived",
               "--output", str(out2)])
    assert rc == 0
    code2 = out2.read_text()
    assert "DataReaders.avro" in code2
    sys.path.insert(0, str(tmp_path))
    import importlib
    mod = importlib.import_module("lead_app")
    assert mod.workflow.result_features
    mod2 = importlib.import_module("passenger_app")
    assert mod2.workflow.result_features


def test_avsc_named_type_reference(tmp_path):
    """A field referencing an earlier named type (standard Avro reuse)
    maps to the same FeatureType as the definition."""
    import json as _json
    from transmogrifai_tpu.cli import main

    avsc = {"type": "record", "name": "R", "fields": [
        {"name": "status", "type": {"type": "enum", "name": "Status",
                                    "symbols": ["a", "b"]}},
        {"name": "status2", "type": "Status"},
        {"name": "label", "type": "double"},
    ]}
    p = tmp_path / "named.avsc"
    p.write_text(_json.dumps(avsc))
    out = tmp_path / "named_app.py"
    assert main(["gen", "--input", str(p), "--response", "label",
                 "--output", str(out)]) == 0
    code = out.read_text()
    assert 'FeatureBuilder.PickList("status")' in code
    assert 'FeatureBuilder.PickList("status2")' in code


def test_nested_named_type_registration():
    from transmogrifai_tpu.data.avro import _Names, avro_ftype
    names = _Names()
    # enum defined inside an array's items, referenced later by name
    assert avro_ftype({"type": "array",
                       "items": {"type": "enum", "name": "Tag",
                                 "namespace": "com.x",
                                 "symbols": ["a", "b"]}}, names) is T.TextList
    assert avro_ftype("Tag", names) is T.PickList
    assert avro_ftype("com.x.Tag", names) is T.PickList


def test_namespace_inheritance():
    from transmogrifai_tpu.data.avro import _Names, register_named_types, avro_ftype
    names = _Names()
    register_named_types({
        "type": "record", "name": "Outer", "namespace": "com.x",
        "fields": [{"name": "tag",
                    "type": {"type": "enum", "name": "Tag",
                             "symbols": ["a"]}}]}, names)
    assert avro_ftype("com.x.Tag", names) is T.PickList
