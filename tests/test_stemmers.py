# -*- coding: utf-8 -*-
"""Per-language light stemmers (r4 VERDICT #6): tokenizer fixtures for
en/fr/de/es/it/pt/nl/ru, CJK-unchanged guarantees, and the
SmartTextVectorizer-pipeline stat-stability property on inflected text
(stemmed inflectional variants hash to the same buckets).

Reference bar: Lucene per-language analyzers with stemmers behind
`TextTokenizer` (`LuceneTextAnalyzer.scala:87`)."""

import numpy as np

import transmogrifai_tpu.types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.ops.text import TextTokenizer
from transmogrifai_tpu.utils.stemmers import stem, stem_tokens


def _col(texts):
    return Column(T.Text, np.array(texts, dtype=object))


class TestStemRules:
    """Inflectional families collapse to ONE form per language."""

    CASES = {
        "en": [("running", "runs", "run"), ("jumped", "jumping", "jumps"),
               ("families", "family"), ("happiness", "happy"),
               ("quickly", "quick")],
        "fr": [("mangeaient", "manger", "mange"),
               ("nationale", "nationales", "national")],
        "de": [("kindern", "kinder", "kind"), ("hauses", "haus"),
               ("machen", "macht", "machst")],
        "es": [("corriendo", "correr", "corre"), ("rápidas", "rápido")],
        "it": [("ragazzi", "ragazzo", "ragazza"), ("parlando", "parlare")],
        "pt": [("falando", "falar", "fala"), ("livros", "livro")],
        "nl": [("katten", "kat"), ("lopen", "loopt")],
        "ru": [("бежала", "бежали"), ("книги", "книга"),
               ("красивый", "красивая", "красивое")],
    }

    def test_families_collapse(self):
        for lang, groups in self.CASES.items():
            for group in groups:
                stems = {stem(w, lang) for w in group}
                assert len(stems) == 1, (lang, group, stems)

    def test_identity_for_unknown_language_and_short_tokens(self):
        assert stem("running", None) == "running"
        assert stem("running", "zz") == "running"
        assert stem("cat", "en") == "cat"  # ≤3 chars never touched
        assert stem_tokens(["foo", "bars"], "xx") == ["foo", "bars"]

    def test_no_overstemming_keeps_stems_nonempty(self):
        for lang, groups in self.CASES.items():
            for group in groups:
                for w in group:
                    s = stem(w, lang)
                    assert len(s) >= 2, (lang, w, s)


class TestTokenizerStemming:
    def test_english_stage_stems_after_stopwords(self):
        out = TextTokenizer(language="en").transform(
            [_col(["The dogs are running quickly through gardens"])])
        assert out.data[0] == ["dog", "run", "quick", "through", "garden"]

    def test_stem_false_opts_out(self):
        out = TextTokenizer(language="en", stem=False).transform(
            [_col(["The dogs were running"])])
        assert out.data[0] == ["dogs", "were", "running"]

    def test_no_language_means_no_stemming(self):
        out = TextTokenizer().transform([_col(["dogs running quickly"])])
        assert out.data[0] == ["dogs", "running", "quickly"]

    def test_auto_detect_stems_per_row(self):
        col = _col(["Die Kinder spielten im Garten hinter dem Hause",
                    "The children were playing in the gardens"])
        out = TextTokenizer(auto_detect_language=True,
                            auto_detect_threshold=0.5).transform([col])
        assert "kind" in out.data[0]      # Kinder → kind (de)
        assert "garden" in out.data[1]    # gardens → garden (en)

    def test_cjk_bigrams_unchanged(self):
        col = _col(["这是一个中文句子", "日本語のテキスト"])
        plain = TextTokenizer().transform([col])
        stemmed = TextTokenizer(language="zh").transform([col])
        assert plain.data[0] == stemmed.data[0]
        auto = TextTokenizer(auto_detect_language=True,
                             auto_detect_threshold=0.5).transform([col])
        assert auto.data[0] == plain.data[0]

    def test_russian_stage(self):
        out = TextTokenizer(language="ru").transform(
            [_col(["Дети читали интересные книги"])])
        # дети is ≤4 after stemming rules; книги/книга collapse
        assert "книг" in out.data[0]


class TestVectorizerStatStability:
    def test_inflected_variants_hash_identically(self):
        """The r4 VERDICT #6 'done' property: with the language-aware
        tokenizer in front of hashing, documents that differ ONLY by
        inflection produce IDENTICAL hashed count vectors — the
        SmartTextVectorizer statistics computed from them cannot drift
        between inflectional variants of the same vocabulary."""
        from transmogrifai_tpu.features.feature import FeatureBuilder
        from transmogrifai_tpu.ops.text import HashingVectorizer

        doc_a = ["the dog runs quickly through gardens",
                 "families enjoyed the jumping competitions"]
        doc_b = ["the dogs run quick through garden",
                 "family enjoys the jump competition"]

        def vecs(tokenizer, docs):
            enc = HashingVectorizer(num_features=64).host_prepare(
                [tokenizer.transform([_col(docs)])])
            return np.asarray(enc["blocks"][0])

        tok = TextTokenizer(language="en")
        np.testing.assert_array_equal(vecs(tok, doc_a), vecs(tok, doc_b))

        # and WITHOUT stemming the variants hash apart — the instability
        # the stemmer exists to remove
        tok_raw = TextTokenizer(language="en", stem=False)
        assert not np.array_equal(vecs(tok_raw, doc_a),
                                  vecs(tok_raw, doc_b))
