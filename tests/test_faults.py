"""Chaos suite for the runtime/ fault-tolerance layer: deterministic
fault injection, bounded retry, kill/resume sweep parity from the block
journal, crash-consistent model artifacts, ingest retry, store
checksums, serving /reload rejection, and lint L008."""

import glob
import json
import os

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.data.columns import Column
from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.runtime.faults import (
    SITE_READ_CHUNK, SITE_RUN_BLOCK, SITE_WRITE_FILE, FaultPlan,
    FaultSpec, InjectedFault, InjectedKill, fault_point, is_oom_error)
from transmogrifai_tpu.runtime.journal import SweepJournal
from transmogrifai_tpu.runtime.retry import RetryEvent, RetryPolicy
from transmogrifai_tpu.selector import ModelSelector
from transmogrifai_tpu.selector.validators import OpCrossValidation
from transmogrifai_tpu.stages.base import FitContext


# --------------------------------------------------------------------------- #
# fault plan / retry policy units                                             #
# --------------------------------------------------------------------------- #

def test_fault_plan_fires_at_nth_pass():
    plan = FaultPlan([FaultSpec("site.a", at=3, kind="error",
                                transient=True)])
    with plan.active():
        fault_point("site.a")
        fault_point("site.a")
        with pytest.raises(InjectedFault) as ei:
            fault_point("site.a")
        assert ei.value.transient and ei.value.n == 3
        fault_point("site.a")  # times=1: pass 4 is clean
        fault_point("site.b")  # other sites unaffected
    fault_point("site.a")  # plan deactivated: free
    assert plan.fired == [("site.a", 3, "error")]


def test_fault_plan_kill_is_base_exception_and_times_forever():
    plan = FaultPlan([FaultSpec("s", at=2, kind="kill", times=0)])
    with plan.active():
        fault_point("s")
        for _ in range(3):  # fires on EVERY pass >= 2
            with pytest.raises(InjectedKill):
                fault_point("s")
    assert not issubclass(InjectedKill, Exception)


def test_oom_fault_recognized():
    plan = FaultPlan([FaultSpec("s", kind="oom")])
    with plan.active():
        with pytest.raises(InjectedFault) as ei:
            fault_point("s")
    assert is_oom_error(ei.value)
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                                     "while allocating 2.1G"))
    assert not is_oom_error(ValueError("shapes do not match"))


def test_retry_policy_bounded_and_classified():
    slept = []
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.01, jitter=0.0,
                         sleep=slept.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient io")
        return "ok"

    events = []
    assert policy.call(flaky, label="t",
                       on_attempt=events.append) == "ok"
    assert calls["n"] == 3 and len(slept) == 2 and len(events) == 2
    assert all(isinstance(e, RetryEvent) for e in events)
    # exponential backoff (jitter disabled): 0.01, 0.02
    assert slept == [pytest.approx(0.01), pytest.approx(0.02)]

    # fatal (unclassified) errors propagate on the FIRST attempt
    calls["n"] = 0

    def fatal():
        calls["n"] += 1
        raise ValueError("deterministic bug")

    with pytest.raises(ValueError):
        policy.call(fatal)
    assert calls["n"] == 1

    # exhaustion re-raises the last underlying error
    def always():
        raise TimeoutError("still down")

    with pytest.raises(TimeoutError):
        policy.call(always)

    # the error's own `transient` attribute classifies injected faults
    def injected():
        raise InjectedFault("s", 1, transient=True)

    with pytest.raises(InjectedFault):
        policy.call(injected)  # retried to exhaustion, then propagates


def test_retry_policy_deterministic_jitter():
    p = RetryPolicy(max_attempts=4, base_delay_s=0.1, jitter=0.5, seed=9,
                    sleep=lambda s: None)
    import random
    a = [p.delay_for(i, random.Random("9:x")) for i in (1, 2, 3)]
    b = [p.delay_for(i, random.Random("9:x")) for i in (1, 2, 3)]
    assert a == b  # same seed+label => same schedule


# --------------------------------------------------------------------------- #
# sweep journal                                                               #
# --------------------------------------------------------------------------- #

def test_sweep_journal_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "fam.journal")
    j = SweepJournal(path, meta={"sig": "abc"})
    row = [0.12345678901234567, float(np.float64(1) / 3)]
    j.append({"reg_param": 0.1}, row, best={"mean": 0.2})
    j.append({"reg_param": 0.2}, [0.5, 0.6])

    # floats round-trip JSON bit-exactly
    j2 = SweepJournal(path, meta={"sig": "abc"})
    assert j2.lookup({"reg_param": 0.1}) == row
    assert len(j2) == 2

    # torn final line (kill mid-append): intact prefix still loads, and
    # the damaged tail is TRUNCATED so post-resume appends don't
    # concatenate onto the garbage (and vanish on the next load)
    with open(path, "a") as fh:
        fh.write('{"key": "deadbeef", "fold_m')
    j3 = SweepJournal(path, meta={"sig": "abc"})
    assert len(j3) == 2
    j3.append({"reg_param": 0.3}, [0.7, 0.8])
    j5 = SweepJournal(path, meta={"sig": "abc"})  # second resume
    assert len(j5) == 3
    assert j5.lookup({"reg_param": 0.3}) == [0.7, 0.8]

    # header meta mismatch: rotated aside, fresh journal
    j4 = SweepJournal(path, meta={"sig": "OTHER"})
    assert len(j4) == 0
    assert os.path.exists(path + ".stale")


def test_sweep_journal_empty_and_header_torn_files(tmp_path):
    # kill between file create and header flush: empty file must get a
    # fresh header on the next append (not headerless records that the
    # following load rotates aside as foreign)
    path = str(tmp_path / "empty.journal")
    open(path, "w").close()
    j = SweepJournal(path, meta={"sig": "s"})
    assert len(j) == 0
    j.append({"g": 1}, [0.1])
    j2 = SweepJournal(path, meta={"sig": "s"})
    assert j2.lookup({"g": 1}) == [0.1]

    # torn HEADER line: truncated to nothing, then rebuilt on append
    path2 = str(tmp_path / "torn-header.journal")
    with open(path2, "w") as fh:
        fh.write('{"journal": 1, "meta"')
    j3 = SweepJournal(path2, meta={"sig": "s"})
    assert len(j3) == 0
    j3.append({"g": 2}, [0.2])
    assert SweepJournal(path2, meta={"sig": "s"}).lookup({"g": 2}) == [0.2]


def test_resume_best_so_far_includes_prekill_blocks(tmp_path):
    """Journal 'best' entries written after a resume must account for
    blocks completed BEFORE the kill."""
    label, vec = _cols()
    plan = FaultPlan([FaultSpec(SITE_RUN_BLOCK, at=2, kind="kill")])
    with pytest.raises(InjectedKill):
        with plan.active():
            _selector(tmp_path / "b").fit_model(
                [label, vec], FitContext(n_rows=200, seed=7))
    _selector(tmp_path / "b").fit_model(
        [label, vec], FitContext(n_rows=200, seed=7))
    journal = glob.glob(str(tmp_path / "b" / "*.journal"))[0]
    recs = [json.loads(x) for x in open(journal) if x.strip()][1:]
    means = {SweepJournal.key_of(r["grid"]): float(np.mean(r["fold_metrics"]))
             for r in recs}
    overall_best = max(means.values())
    # the LAST record's best-so-far must equal the overall best mean —
    # it can only do so if pre-kill blocks seeded the tracker
    assert recs[-1]["best"]["mean"] == pytest.approx(overall_best)


# --------------------------------------------------------------------------- #
# kill/resume sweep parity                                                    #
# --------------------------------------------------------------------------- #

def _cols(n=200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(0, 0.4, n) > 0) \
        .astype(np.float64)
    label = Column(T.RealNN, {"value": y, "mask": np.ones(n, bool)})
    vec = Column(T.OPVector, X)
    return label, vec


def _selector(ckpt_dir):
    # one family, TWO static groups (= two sweep blocks: max_iter 8 / 4)
    grids = [{"reg_param": 0.01, "max_iter": 8},
             {"reg_param": 0.1, "max_iter": 8},
             {"reg_param": 0.02, "max_iter": 4}]
    return ModelSelector(
        models=[(OpLogisticRegression(), grids)],
        validator=OpCrossValidation(n_folds=2, seed=5),
        evaluator=BinaryClassificationEvaluator(),
        checkpoint_dir=str(ckpt_dir))


def _results(model):
    s = model.summary
    return (s.best_grid, [r.fold_metrics for r in s.validation_results])


def test_kill_resume_parity_bit_identical(tmp_path):
    from transmogrifai_tpu.analysis.retrace import MONITOR
    label, vec = _cols()
    ctx = lambda: FitContext(n_rows=200, seed=7)  # noqa: E731

    clean_best, clean_metrics = _results(
        _selector(tmp_path / "clean").fit_model([label, vec], ctx()))

    # kill at the SECOND grid block: block 1 (2 configs) is journaled
    plan = FaultPlan([FaultSpec(SITE_RUN_BLOCK, at=2, kind="kill")])
    with pytest.raises(InjectedKill):
        with plan.active():
            _selector(tmp_path / "f").fit_model([label, vec], ctx())
    journals = glob.glob(str(tmp_path / "f" / "*.journal"))
    assert len(journals) == 1
    lines = [json.loads(x) for x in open(journals[0]) if x.strip()]
    assert lines[0]["journal"] == 1
    assert len(lines) - 1 == 2  # both block-1 configs committed
    assert all("best" in rec for rec in lines[1:])

    # resume: only the un-journaled block runs — asserted two ways:
    # the completed block's program label must NOT re-trace, and the
    # resumed results must be bit-identical to the clean run's
    before = MONITOR.snapshot()
    resumed_best, resumed_metrics = _results(
        _selector(tmp_path / "f").fit_model([label, vec], ctx()))
    delta = MONITOR.delta(before)
    done_labels = [k for k in delta if k.startswith("sweep:logistic:(8,")]
    assert not done_labels, \
        f"resume re-traced completed block shapes: {done_labels}"
    assert resumed_best == clean_best
    assert resumed_metrics == clean_metrics


def test_transient_block_fault_retried_within_family(tmp_path):
    """A TRANSIENT error at a block boundary is absorbed by the family
    retry policy (journaled blocks are skipped on the retry), not
    surfaced and not dropped."""
    label, vec = _cols()
    plan = FaultPlan([FaultSpec(SITE_RUN_BLOCK, at=2, kind="error",
                                transient=True)])
    with plan.active():
        best, metrics = _results(
            _selector(tmp_path / "t").fit_model(
                [label, vec], FitContext(n_rows=200, seed=7)))
    clean_best, clean_metrics = _results(
        _selector(tmp_path / "c").fit_model(
            [label, vec], FitContext(n_rows=200, seed=7)))
    assert plan.fired == [(SITE_RUN_BLOCK, 2, "error")]
    assert best == clean_best and metrics == clean_metrics


def test_oom_block_halves_width_and_completes(tmp_path):
    """A device-OOM-shaped failure on a multi-config block degrades to
    two half-width blocks instead of surfacing."""
    label, vec = _cols()
    plan = FaultPlan([FaultSpec(SITE_RUN_BLOCK, at=1, kind="oom")])
    with plan.active():
        best, metrics = _results(
            _selector(tmp_path / "o").fit_model(
                [label, vec], FitContext(n_rows=200, seed=7)))
    clean_best, clean_metrics = _results(
        _selector(tmp_path / "c").fit_model(
            [label, vec], FitContext(n_rows=200, seed=7)))
    assert plan.fired == [(SITE_RUN_BLOCK, 1, "oom")]
    assert plan.count(SITE_RUN_BLOCK) >= 3  # block 1 re-ran as halves
    assert best == clean_best and metrics == clean_metrics


# --------------------------------------------------------------------------- #
# crash-consistent artifacts                                                  #
# --------------------------------------------------------------------------- #

def _tiny_model():
    from transmogrifai_tpu.automl import transmogrify
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.workflow import Workflow
    rng = np.random.default_rng(1)
    n = 80
    # 40 features: the fitted weight matrix crosses NPZ_MIN_SIZE, so the
    # saved artifact includes arrays.npz (the corruption targets need it)
    cols = {f"a{i}": rng.normal(size=n) for i in range(40)}
    cols["label"] = rng.integers(0, 2, n).astype(np.float64)
    schema = {k: T.Real for k in cols}
    schema["label"] = T.Integral
    ds = Dataset(cols, schema)
    preds, label = FeatureBuilder.from_dataset(ds, response="label")
    vec = transmogrify(preds)
    pred = OpLogisticRegression(max_iter=5).set_input(label, vec) \
        .get_output()
    return Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train()


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    base = tmp_path_factory.mktemp("fault-models")
    model = _tiny_model()
    path = str(base / "model")
    model.save(path)
    return model, path


def test_save_writes_integrity_manifest(saved_model):
    _, path = saved_model
    with open(os.path.join(path, "integrity.json")) as fh:
        integ = json.load(fh)
    assert "op-model.json" in integ["files"]
    for rec in integ["files"].values():
        assert rec["sha256"] and rec["bytes"] > 0


def test_save_killed_mid_write_leaves_old_model_intact(saved_model,
                                                       tmp_path):
    from transmogrifai_tpu.workflow.serialization import (
        load_model, model_fingerprint, save_model)
    model, _ = saved_model
    path = str(tmp_path / "m")
    save_model(model, path)
    fp = model_fingerprint(path)
    for n in (1, 2, 3):  # kill at every write site pass
        plan = FaultPlan([FaultSpec(SITE_WRITE_FILE, at=n, kind="kill")])
        with pytest.raises(InjectedKill):
            with plan.active():
                save_model(model, path, overwrite=True)
        assert model_fingerprint(path) == fp
        load_model(path)  # still verifies + loads
        assert not glob.glob(path + ".tmp-*") or True  # tmp may linger


def test_overwrite_false_still_raises(saved_model):
    from transmogrifai_tpu.workflow.serialization import save_model
    model, path = saved_model
    with pytest.raises(FileExistsError):
        save_model(model, path, overwrite=False)


@pytest.mark.parametrize("corruption", ["truncate_npz", "bitflip_manifest",
                                        "drop_integrity", "drop_npz"])
def test_load_model_rejects_torn_or_corrupt(saved_model, tmp_path,
                                            corruption):
    import shutil

    from transmogrifai_tpu.workflow.serialization import (
        ModelIntegrityError, load_model, save_model)
    model, _ = saved_model
    path = str(tmp_path / "m")
    save_model(model, path)
    npz = os.path.join(path, "arrays.npz")
    if corruption == "truncate_npz":
        size = os.path.getsize(npz)
        with open(npz, "r+b") as fh:
            fh.truncate(size // 2)
    elif corruption == "bitflip_manifest":
        mpath = os.path.join(path, "op-model.json")
        data = bytearray(open(mpath, "rb").read())
        data[len(data) // 2] ^= 0x40
        open(mpath, "wb").write(bytes(data))
    elif corruption == "drop_integrity":
        os.unlink(os.path.join(path, "integrity.json"))
    else:
        os.unlink(npz)
    with pytest.raises(ModelIntegrityError) as ei:
        load_model(path)
    assert path in str(ei.value)
    shutil.rmtree(path)


# --------------------------------------------------------------------------- #
# ingest retry                                                                #
# --------------------------------------------------------------------------- #

def _run_pipeline(store, retry=None, stats=None):
    from transmogrifai_tpu.data.pipeline import run_chunk_pipeline
    chunks = []
    stats = run_chunk_pipeline(
        range(0, store.n_rows, 64),
        lambda r0: np.array(store.chunk(r0, r0 + 64), copy=True),
        lambda c: chunks.append(c),
        workers=2, depth=2, retry=retry, stats=stats)
    return np.concatenate(chunks), stats


@pytest.fixture(scope="module")
def small_store(tmp_path_factory):
    from transmogrifai_tpu.data.columnar_store import synth_binary_store
    tmp = tmp_path_factory.mktemp("fault-store")
    return synth_binary_store(str(tmp / "store"), 512, 8, seed=2,
                              chunk_rows=128)


def test_ingest_retry_until_success_bitwise(small_store):
    ref, ref_stats = _run_pipeline(small_store)
    assert ref_stats.retries == 0

    policy = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                         sleep=lambda s: None)
    plan = FaultPlan([FaultSpec(SITE_READ_CHUNK, at=2, kind="error",
                                transient=True, times=2)])
    with plan.active():
        out, stats = _run_pipeline(small_store, retry=policy)
    # passes 2 and 3 failed (chunk 2's first try + first retry), the
    # second retry succeeded: budget of 3 attempts absorbs both
    assert stats.retries == 2
    assert stats.retry_wait_s >= 0.0
    assert out.tobytes() == ref.tobytes()  # bitwise-identical output


def test_ingest_retry_exhausted_propagates(small_store):
    policy = RetryPolicy(max_attempts=2, base_delay_s=0.001,
                         sleep=lambda s: None)
    plan = FaultPlan([FaultSpec(SITE_READ_CHUNK, at=2, kind="error",
                                transient=True, times=0)])
    with plan.active():
        with pytest.raises(InjectedFault):
            _run_pipeline(small_store, retry=policy)


def test_ingest_fatal_fault_not_retried(small_store):
    from transmogrifai_tpu.data.pipeline import IngestStats
    policy = RetryPolicy(max_attempts=5, base_delay_s=0.001,
                         sleep=lambda s: None)
    plan = FaultPlan([FaultSpec(SITE_READ_CHUNK, at=1, kind="error",
                                transient=False)])
    stats = IngestStats()
    with plan.active():
        with pytest.raises(InjectedFault):
            _run_pipeline(small_store, retry=policy, stats=stats)
    assert stats.retries == 0  # fatal: surfaced on the first attempt


def test_bigdata_upload_records_retries(small_store):
    jax = pytest.importorskip("jax")
    from transmogrifai_tpu.parallel import bigdata as bd
    ref = np.asarray(bd.device_matrix(small_store, chunk_rows=128))
    plan = FaultPlan([FaultSpec(SITE_READ_CHUNK, at=2, kind="error",
                                transient=True)])
    with plan.active():
        buf, stats = bd.device_matrix(
            small_store, chunk_rows=128, return_stats=True,
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.001,
                              sleep=lambda s: None))
    assert stats.retries == 1
    assert stats.to_extra()["retries"] == 1
    np.testing.assert_array_equal(np.asarray(buf), ref)


# --------------------------------------------------------------------------- #
# columnar store checksums                                                    #
# --------------------------------------------------------------------------- #

def test_store_truncated_column_file_is_structured_error(tmp_path):
    from transmogrifai_tpu.data.columnar_store import (
        ColumnarStore, StoreIntegrityError, synth_binary_store)
    store = synth_binary_store(str(tmp_path / "s"), 256, 4, seed=1,
                               chunk_rows=64)
    xpath = os.path.join(store.path, "X.bin")
    size = os.path.getsize(xpath)
    with open(xpath, "r+b") as fh:
        fh.truncate(size - 64)
    with pytest.raises(StoreIntegrityError) as ei:
        ColumnarStore(store.path)
    msg = str(ei.value)
    assert "X.bin" in msg and "truncated" in msg and "column" in msg


def test_store_bitflip_detected_and_optout(tmp_path):
    from transmogrifai_tpu.data.columnar_store import (
        ColumnarStore, StoreIntegrityError, synth_binary_store)
    store = synth_binary_store(str(tmp_path / "s"), 256, 4, seed=1,
                               chunk_rows=64)
    xpath = os.path.join(store.path, "X.bin")
    with open(xpath, "r+b") as fh:
        fh.seek(100)
        b = fh.read(1)
        fh.seek(100)
        fh.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(StoreIntegrityError) as ei:
        ColumnarStore(store.path)
    assert "checksum mismatch" in str(ei.value)
    st = ColumnarStore(store.path, verify=False)  # explicit opt-out
    assert st.n_rows == 256


def test_store_label_file_named_in_error(tmp_path):
    from transmogrifai_tpu.data.columnar_store import (
        ColumnarStore, StoreIntegrityError, synth_binary_store)
    store = synth_binary_store(str(tmp_path / "s"), 128, 4, seed=1,
                               chunk_rows=64)
    ypath = os.path.join(store.path, "y.bin")
    with open(ypath, "r+b") as fh:
        fh.truncate(os.path.getsize(ypath) - 8)
    with pytest.raises(StoreIntegrityError) as ei:
        ColumnarStore(store.path)
    assert "y.bin" in str(ei.value) and "label column" in str(ei.value)


# --------------------------------------------------------------------------- #
# serving: /reload of a corrupt dir keeps the resident version               #
# --------------------------------------------------------------------------- #

def test_reload_corrupt_dir_keeps_resident_serving(saved_model, tmp_path):
    from transmogrifai_tpu.serving import (
        ScoreError, ScoringService, ServingConfig)
    from transmogrifai_tpu.workflow.serialization import save_model
    model, v1_path = saved_model
    v2 = str(tmp_path / "v2")
    save_model(model, v2)
    npz = os.path.join(v2, "arrays.npz")
    with open(npz, "r+b") as fh:
        fh.truncate(os.path.getsize(npz) // 2)

    service = ScoringService.from_path(
        v1_path, config=ServingConfig(max_batch=4, batch_wait_ms=1.0))
    service.start()
    try:
        resident = service.health()["model_version"]
        row = {f"a{i}": 0.1 * i for i in range(40)}
        before = service.score_row(row)
        with pytest.raises(ScoreError) as ei:
            service.reload(v2)
        assert ei.value.code == "bad_request"
        assert "resident version keeps serving" in str(ei.value)
        # resident version untouched and still answering, byte-for-byte
        assert service.health()["model_version"] == resident
        assert service.score_row(row) == before
        assert service.registry.counter(
            "serving_reload_rejected_total",
            "reloads rejected by artifact integrity verification"
        ).value == 1
    finally:
        service.stop()


# --------------------------------------------------------------------------- #
# workflow params wiring + lint L008                                          #
# --------------------------------------------------------------------------- #

def test_sweep_checkpoint_params_json_roundtrip():
    from transmogrifai_tpu.workflow.params import (
        OpParams, SweepCheckpointParams)
    p = OpParams(sweep_checkpoint=SweepCheckpointParams(
        checkpoint_dir="/tmp/ckpt", fsync=False))
    d = p.to_json()
    back = OpParams.from_json(d)
    assert back.sweep_checkpoint.checkpoint_dir == "/tmp/ckpt"
    assert back.sweep_checkpoint.fsync is False
    assert OpParams.from_json({}).sweep_checkpoint is None


def test_workflow_threads_checkpoint_dir_to_selector(tmp_path):
    from transmogrifai_tpu.automl import transmogrify
    from transmogrifai_tpu.data import Dataset
    from transmogrifai_tpu.features import FeatureBuilder
    from transmogrifai_tpu.selector.model_selector import (
        BinaryClassificationModelSelector)
    from transmogrifai_tpu.workflow import Workflow
    rng = np.random.default_rng(4)
    n = 120
    ds = Dataset(
        {"a": rng.normal(size=n), "b": rng.normal(size=n),
         "label": rng.integers(0, 2, n).astype(np.float64)},
        {"a": T.Real, "b": T.Real, "label": T.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="label")
    vec = transmogrify(preds)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        models=[(OpLogisticRegression(max_iter=4),
                 [{"reg_param": 0.01}, {"reg_param": 0.1}])],
        n_folds=2, splitter=None)
    pred = selector.set_input(label, vec).get_output()
    ckpt = str(tmp_path / "ckpt")
    wf = Workflow().set_result_features(pred, label) \
        .set_parameters({"sweep_checkpoint": {"checkpoint_dir": ckpt}}) \
        .set_input_dataset(ds)
    wf.train()
    assert glob.glob(os.path.join(ckpt, "sweep_*.json")), \
        "selector did not pick up the sweep_checkpoint params"
    assert glob.glob(os.path.join(ckpt, "sweep_*.journal"))
    # the user's own selector instance is untouched (train clones)
    assert selector.checkpoint_dir is None


def test_lint_L008_flags_and_allows():
    from transmogrifai_tpu.analysis.lint import lint_source
    bad = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except:\n"
        "        ...\n"
        "def r():\n"
        "    while True:\n"
        "        try:\n"
        "            return g()\n"
        "        except ValueError:\n"
        "            continue\n"
    )
    findings = [f for f in lint_source(bad) if f.code == "L008"]
    assert len(findings) == 3, findings

    good = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except ValueError:\n"
        "        pass\n"              # narrowed type: allowed
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        log.debug('x', exc_info=True)\n"
        "def r():\n"
        "    attempts = 0\n"
        "    while True:\n"
        "        try:\n"
        "            return g()\n"
        "        except ValueError:\n"
        "            attempts += 1\n"
        "            if attempts > 3:\n"
        "                raise\n"      # bounded: handler can exit
        "def stream(buf):\n"
        "    while True:\n"           # no handler inside: allowed
        "        if not buf.read(1):\n"
        "            break\n"
    )
    assert [f for f in lint_source(good) if f.code == "L008"] == []
