"""Multi-chip path: Workflow.train(mesh=...) through the REAL framework.

VERDICT r1 #2: the multichip dryrun and tests must exercise the framework's
own training path — transmogrify → SanityChecker → ModelSelector sweep —
over a jax.sharding.Mesh, not a hand-rolled logistic sweep. The conftest
forces 8 virtual CPU devices, mirroring the reference's `local[2]` trick.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # 8-virtual-device mesh sweeps

import __graft_entry__ as ge
from transmogrifai_tpu.parallel.mesh import DATA_AXIS, SWEEP_AXIS, make_mesh
from transmogrifai_tpu.workflow import Workflow


def _train(mesh=None, n_rows=256):
    ds = ge._make_dataset(n_rows)
    pf, label = ge._build_pipeline(ds, tiny=True)
    model = (Workflow()
             .set_result_features(pf, label)
             .set_input_dataset(ds)
             .train(mesh=mesh))
    fitted = model.fitted[pf.origin_stage.uid]
    return model, fitted.summary, pf


@pytest.mark.parametrize("n_rows", [256, 301])
def test_mesh_sharded_sweep_matches_unsharded(n_rows):
    """Same seeds → the sharded sweep must reproduce the single-device
    metric matrix (collectives only change WHERE the math runs) — including
    on row counts NOT divisible by the data axis (zero-weight padding +
    unpadded quantile binning + prefix-stable bootstrap streams)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(8, sweep=4)
    assert mesh.shape == {SWEEP_AXIS: 4, DATA_AXIS: 2}

    _, base, _ = _train(mesh=None, n_rows=n_rows)
    _, sharded, _ = _train(mesh=mesh, n_rows=n_rows)

    assert base.best_model == sharded.best_model
    base_rows = {(r.model, tuple(sorted(r.grid.items()))): r.fold_metrics
                 for r in base.validation_results}
    shard_rows = {(r.model, tuple(sorted(r.grid.items()))): r.fold_metrics
                  for r in sharded.validation_results}
    assert set(base_rows) == set(shard_rows)
    for key, fm in base_rows.items():
        np.testing.assert_allclose(fm, shard_rows[key], rtol=2e-4, err_msg=str(key))


def test_sweep_axis_sharded_grid_parity():
    """A grid group whose size divides the sweep axis goes through the
    device_put(sweep_sharding) branch of _shard_dyn — assert it matches the
    unsharded metric matrix (covers the grid-axis spread itself)."""
    import jax
    import jax.numpy as jnp
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.parallel.mesh import sweep_sharding
    from transmogrifai_tpu.parallel.sweep import run_sweep
    from transmogrifai_tpu.selector.validators import OpCrossValidation
    from transmogrifai_tpu.stages.base import FitContext

    rng = np.random.default_rng(5)
    n, d = 240, 6
    X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    y_np = (rng.uniform(size=n) > 0.5).astype(np.float64)
    y = jnp.asarray(y_np.astype(np.float32))
    folds = OpCrossValidation(n_folds=2, seed=0).splits(y_np)
    est = OpLogisticRegression(max_iter=10)
    grids = [{"reg_param": r} for r in (0.001, 0.01, 0.1, 0.2)]  # 4 ÷ sweep=4
    ev = BinaryClassificationEvaluator()

    base = run_sweep(est, grids, X, y, folds, ev, FitContext(n_rows=n))
    mesh = make_mesh(8, sweep=4)
    ctx = FitContext(n_rows=n, mesh=mesh)
    sharded = run_sweep(est, grids, X, y, folds, ev, ctx,
                        sharding=sweep_sharding(mesh))
    np.testing.assert_allclose(np.asarray(base), np.asarray(sharded), rtol=2e-4)


def test_mesh_train_covers_default_families():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(8, sweep=2)
    _, summary, _ = _train(mesh=mesh)
    families = {r.model for r in summary.validation_results}
    assert {"OpLogisticRegression", "OpRandomForestClassifier",
            "OpXGBoostClassifier"} <= families
    assert all(np.isfinite(r.mean_metric) for r in summary.validation_results)


def test_mesh_scoring_parity():
    """Fused scoring of a mesh-trained model matches a plain-trained one."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(8, sweep=4)
    m0, _, pf0 = _train(mesh=None)
    m1, _, pf1 = _train(mesh=mesh)
    ds = ge._make_dataset(256)
    p0 = np.asarray(m0.score_compiled(ds)[pf0.name]["prediction"])
    p1 = np.asarray(m1.score_compiled(ds)[pf1.name]["prediction"])
    assert (p0 == p1).mean() > 0.98  # identical up to float-reduction order


def test_dryrun_multichip_entry():
    """The driver artifact itself (asserts internally)."""
    ge.dryrun_multichip(8)


def test_multislice_mesh_topology():
    """Slice boundaries land on the sweep axis (DCN-friendly); the data
    axis stays within a slice (ICI psums)."""
    from transmogrifai_tpu.parallel.mesh import (
        DATA_AXIS, SWEEP_AXIS, make_multislice_mesh)

    mesh = make_multislice_mesh(n_slices=2, data_per_slice=2)
    assert mesh.axis_names == (SWEEP_AXIS, DATA_AXIS)
    assert mesh.shape[SWEEP_AXIS] == 4 and mesh.shape[DATA_AXIS] == 2
    # each data row is contiguous devices (same virtual "slice")
    grid = mesh.devices
    ids = np.array([[d.id for d in row] for row in grid])
    for row in ids:
        assert row[1] == row[0] + 1
    # sweep rows 0-1 come from slice 0's devices, rows 2-3 from slice 1
    assert ids[:2].max() < ids[2:].min()


def test_multislice_mesh_trains():
    from transmogrifai_tpu.parallel.mesh import make_multislice_mesh
    from transmogrifai_tpu.workflow import Workflow
    import __graft_entry__ as g

    mesh = make_multislice_mesh(n_slices=2, data_per_slice=2)
    ds = g._make_dataset(n=256)
    pf, label = g._build_pipeline(ds, tiny=True)
    model = (Workflow().set_result_features(pf, label)
             .set_input_dataset(ds).train(mesh=mesh))
    summary = model.fitted[pf.origin_stage.uid].summary
    assert np.isfinite([r.mean_metric for r in summary.validation_results]).all()


def test_sharded_batch_scoring_parity():
    """score_compiled(sharding=data_sharding(mesh)) spreads the batch over
    the mesh and matches unsharded scores (r1 weak#4: the arg used to be
    dead)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from transmogrifai_tpu.parallel.mesh import data_sharding

    model, ds, pf = ge._fit_flagship(n=256)
    base = np.asarray(model.score_compiled(ds)[pf.name]["prediction"])
    mesh = make_mesh(8, sweep=1)  # all devices on the data axis
    sh = data_sharding(mesh)
    out = model.score_compiled(ds, sharding=sh)
    sharded = np.asarray(out[pf.name]["prediction"])
    np.testing.assert_array_equal(base, sharded)


def test_score_stream_data_sharded_parity_and_distribution(tmp_path):
    """VERDICT r4 #8: the 1B-row streaming claim rests on the data axis —
    prove `score_stream(sharding=data)` (a) actually DISTRIBUTES each
    batch's fused program over the mesh and (b) yields per-batch outputs
    equal to the unsharded stream, through the grouped-fetch path."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from transmogrifai_tpu.parallel.mesh import data_sharding
    from transmogrifai_tpu.readers import DataReaders

    model, ds, pf = ge._fit_flagship(n=512)
    pq = str(tmp_path / "stream.parquet")
    ds.to_parquet(pq)
    # batch 128 = 16 rows/shard on the 8-wide data axis
    reader = DataReaders.stream(parquet_path=pq, batch_size=128,
                                schema=dict(ds.schema))

    base = [np.asarray(out[pf.name]["prediction"])
            for out in model.score_stream(reader.stream(), fetch_group=3)]
    assert len(base) == 4 and all(len(b) == 128 for b in base)

    mesh = make_mesh(8, sweep=1)  # every device on the data axis
    sh = data_sharding(mesh)
    sharded = [np.asarray(out[pf.name]["prediction"])
               for out in model.score_stream(reader.stream(), sharding=sh,
                                             fetch_group=3)]
    assert len(sharded) == len(base)
    for b, s in zip(base, sharded):
        np.testing.assert_array_equal(b, s)

    # distribution proof: the fused program's batch inputs AND outputs
    # live sharded across all 8 devices (XLA ran the row axis SPMD —
    # per-device dispatch concurrency, not one chip doing all the work)
    scorer = model._compiled
    assert scorer.sharding is sh
    batch = next(iter(reader.stream()))
    encs, raw_dev, _ = scorer.host_phase(batch)
    sharded_inputs = [
        leaf for leaf in jax.tree_util.tree_leaves(raw_dev)
        if hasattr(leaf, "sharding")
        and getattr(leaf, "shape", ()) and leaf.shape[0] == 128
        and len(leaf.sharding.device_set) == 8]
    assert sharded_inputs, "no batch-axis input was placed on the mesh"
    out = scorer.fused_jitted()(scorer._consts, encs, raw_dev)
    pred = out[pf.uid]["prediction"]
    assert len(pred.sharding.device_set) == 8, pred.sharding


def test_mesh_sweep_early_stopped_xgb_and_rf_grid_parity():
    """VERDICT r3 #6: the REAL sweep machinery under a mesh — an
    early-stopped XGB config (the in-scan masking path, which single-
    device runs bypass via round-chunked host dispatch) and a full
    18-config RF grid with the reference's {3,6,12} depth axis (grouped
    static shapes + depth buckets) — must reproduce single-device
    metrics. Reference semantics: OpCrossValidation.scala:87-147."""
    import jax
    import jax.numpy as jnp
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
    from transmogrifai_tpu.models import (
        OpRandomForestClassifier, OpXGBoostClassifier)
    from transmogrifai_tpu.parallel.mesh import sweep_sharding
    from transmogrifai_tpu.parallel.sweep import run_sweep
    from transmogrifai_tpu.selector.validators import OpCrossValidation
    from transmogrifai_tpu.stages.base import FitContext

    rng = np.random.default_rng(11)
    n, d = 512, 8
    X_np = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d)
    y_np = ((X_np @ w + 0.5 * rng.normal(size=n)) > 0).astype(np.float64)
    X = jnp.asarray(X_np)
    y = jnp.asarray(y_np.astype(np.float32))
    folds = OpCrossValidation(n_folds=3, seed=0).splits(y_np)
    ev = BinaryClassificationEvaluator()
    mesh = make_mesh(8, sweep=4)

    # the reference 18-config RF shape: depth {3,6,12} × minInstances
    # {10,100} × impurity-stand-in axis (scaled trees for CPU runtime)
    rf = OpRandomForestClassifier(n_trees=8, max_bins=16)
    rf_grids = [{"max_depth": dpt, "min_instances_per_node": mi,
                 "min_info_gain": gi}
                for dpt in (3, 6, 12) for mi in (1.0, 10.0)
                for gi in (0.0, 0.01, 0.1)]
    assert len(rf_grids) == 18
    # early-stopped XGB (reference: 200 rounds / esr 20, scaled here)
    xgb = OpXGBoostClassifier(n_estimators=40, max_bins=16,
                              early_stopping_rounds=5)
    xgb_grids = [{"eta": 0.3, "max_depth": 3}, {"eta": 0.1, "max_depth": 6}]

    for est, grids in ((rf, rf_grids), (xgb, xgb_grids)):
        base = np.asarray(run_sweep(est, grids, X, y, folds, ev,
                                    FitContext(n_rows=n, seed=7)), np.float64)
        ctx = FitContext(n_rows=n, seed=7, mesh=mesh)
        sharded = np.asarray(run_sweep(est, grids, X, y, folds, ev, ctx,
                                       sharding=sweep_sharding(mesh)),
                             np.float64)
        # deep unconstrained trees (depth 12, min_instances 1) flip a few
        # near-tie splits under the mesh's different reduction order —
        # measured ≤1.1e-3 metric drift on 3/54 entries; everything else
        # is reduction-order exact
        np.testing.assert_allclose(base, sharded, atol=2e-3,
                                   err_msg=type(est).__name__)
        frac_exact = (np.abs(base - sharded) <= 2e-4).mean()
        assert frac_exact >= 0.9, (type(est).__name__, frac_exact)
        assert np.isfinite(base).all()
