"""Aggregators + readers: monoid semantics, cutoffs, joins, streaming.

Mirrors reference specs: FeatureAggregatorTest / DataReadersTest /
JoinedDataReaderDataGenerationTest (readers/src/test).
"""

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.aggregators import (
    CutOffTime, Event, aggregate_events, concat_agg, default_aggregator,
    first_agg, last_agg, mean_agg, mode_agg, union_map_agg, sum_agg)
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.readers import DataReaders, JoinedDataReader
from transmogrifai_tpu.workflow import Workflow


def ev(value, t=0):
    return Event(t, value)


class TestAggregators:
    def test_sum_real(self):
        agg = default_aggregator(T.Real)
        assert agg([ev(1.5), ev(2.5), ev(None)]) == 4.0
        assert agg([]) is None

    def test_sum_integral_stays_int(self):
        agg = default_aggregator(T.Integral)
        out = agg([ev(2), ev(3)])
        assert out == 5 and isinstance(out, int)

    def test_percent_mean(self):
        assert default_aggregator(T.Percent)([ev(0.2), ev(0.6)]) == pytest.approx(0.4)

    def test_binary_or(self):
        agg = default_aggregator(T.Binary)
        assert agg([ev(False), ev(True)]) is True
        assert agg([ev(False), ev(False)]) is False

    def test_date_max(self):
        assert default_aggregator(T.Date)([ev(100), ev(500), ev(300)]) == 500

    def test_text_concat(self):
        assert default_aggregator(T.Text)([ev("a"), ev(None), ev("b")]) == "a b"

    def test_picklist_mode_tie_breaks_lexicographic(self):
        agg = default_aggregator(T.PickList)
        assert agg([ev("b"), ev("a"), ev("b")]) == "b"
        assert agg([ev("b"), ev("a")]) == "a"

    def test_multipicklist_union(self):
        assert default_aggregator(T.MultiPickList)(
            [ev({"x", "y"}), ev({"y", "z"})]) == {"x", "y", "z"}

    def test_textlist_concat(self):
        assert default_aggregator(T.TextList)(
            [ev(["a"]), ev(["b", "c"])]) == ["a", "b", "c"]

    def test_real_map_union_sums(self):
        agg = default_aggregator(T.RealMap)
        assert agg([ev({"a": 1.0, "b": 2.0}), ev({"b": 3.0, "c": 4.0})]) == \
            {"a": 1.0, "b": 5.0, "c": 4.0}

    def test_text_map_union_concat(self):
        agg = default_aggregator(T.TextMap)
        assert agg([ev({"a": "x"}), ev({"a": "y", "b": "z"})]) == \
            {"a": "x y", "b": "z"}

    def test_geolocation_midpoint(self):
        agg = default_aggregator(T.Geolocation)
        out = agg([ev([0.0, 0.0, 1.0]), ev([0.0, 90.0, 5.0])])
        assert out[0] == pytest.approx(0.0, abs=1e-6)
        assert out[1] == pytest.approx(45.0, abs=1e-6)
        assert out[2] == 5.0  # accuracy keeps the max, not the sum

    def test_first_last(self):
        evs = [Event(5, "late"), Event(1, "early"), Event(3, "mid")]
        assert first_agg()(evs) == "early"
        assert last_agg()(evs) == "late"

    def test_cutoff_split(self):
        evs = [Event(t, float(t)) for t in range(10)]
        cut = CutOffTime.unix_epoch(5)
        pred = aggregate_events(evs, T.Real, cutoff=cut, is_response=False)
        resp = aggregate_events(evs, T.Real, cutoff=cut, is_response=True)
        assert pred == sum(range(5))      # strictly before cutoff
        assert resp == sum(range(5, 10))  # at/after cutoff

    def test_cutoff_window(self):
        evs = [Event(t, 1.0) for t in range(10)]
        cut = CutOffTime.unix_epoch(8)
        out = aggregate_events(evs, T.Real, cutoff=cut, window_ms=3)
        assert out == 3.0  # times 5,6,7 only

    def test_days_ago(self):
        now = 1_000 * 86_400_000
        c = CutOffTime.days_ago(10, now)
        assert c.timestamp == 990 * 86_400_000


EVENTS = [
    # key, time(day), amount, tag, converted
    {"id": "a", "day": 1, "amount": 10.0, "tag": "x", "converted": 0},
    {"id": "a", "day": 2, "amount": 5.0, "tag": "y", "converted": 0},
    {"id": "a", "day": 8, "amount": 99.0, "tag": "z", "converted": 1},
    {"id": "b", "day": 3, "amount": 2.0, "tag": "x", "converted": 0},
    {"id": "b", "day": 9, "amount": 50.0, "tag": "x", "converted": 0},
]


def _raw_features():
    amount = FeatureBuilder.Real("amount").from_column("amount").as_predictor()
    tag = FeatureBuilder.PickList("tag").from_column("tag").as_predictor()
    label = FeatureBuilder.Binary("converted").from_column("converted").as_response()
    return amount, tag, label


class TestAggregateReader:
    def test_cutoff_semantics(self):
        amount, tag, label = _raw_features()
        reader = DataReaders.aggregate(
            EVENTS, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"],
            cutoff=CutOffTime.unix_epoch(5))
        ds = reader.read([amount, tag, label])
        assert ds.pre_extracted
        rows = {r["key"]: r for r in ds.to_rows()}
        # 'a': predictors fold days 1,2; response folds day 8
        assert rows["a"]["amount"] == 15.0
        assert rows["a"]["converted"] == 1.0
        # 'b': predictor day 3; response day 9 (converted=0 → False)
        assert rows["b"]["amount"] == 2.0
        assert rows["b"]["converted"] == 0.0

    def test_workflow_integration(self):
        amount, tag, label = _raw_features()
        reader = DataReaders.aggregate(
            EVENTS, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"],
            cutoff=CutOffTime.unix_epoch(5))
        from transmogrifai_tpu.ops.numeric import RealVectorizer
        vec = RealVectorizer().set_input(amount).get_output()
        model = Workflow().set_result_features(vec).set_reader(reader).train()
        assert model is not None

    def test_custom_aggregator_via_builder(self):
        amount = FeatureBuilder.Real("amount").from_column("amount") \
            .aggregate(mean_agg()).as_predictor()
        reader = DataReaders.aggregate(
            EVENTS, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"])
        ds = reader.read([amount])
        rows = {r["key"]: r for r in ds.to_rows()}
        assert rows["a"]["amount"] == pytest.approx((10 + 5 + 99) / 3)


class TestConditionalReader:
    def test_condition_sets_per_key_cutoff(self):
        amount, tag, label = _raw_features()
        reader = DataReaders.conditional(
            EVENTS, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"],
            target_condition=lambda r: r["converted"] == 1,
            drop_if_not_met=True)
        ds = reader.read([amount, tag, label])
        rows = {r["key"]: r for r in ds.to_rows()}
        # only 'a' has a converting event (day 8): predictors fold days < 8
        assert set(rows) == {"a"}
        assert rows["a"]["amount"] == 15.0
        assert rows["a"]["converted"] == 1.0

    def test_unmatched_kept_by_default(self):
        # reference parity: dropIfTargetConditionNotMet defaults to FALSE
        # (ConditionalParams, DataReader.scala:375)
        amount, tag, label = _raw_features()
        reader = DataReaders.conditional(
            EVENTS, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"],
            target_condition=lambda r: r["converted"] == 1)
        ds = reader.read([amount, tag, label])
        assert {r["key"] for r in ds.to_rows()} == {"a", "b"}

    def test_time_stamp_to_keep_min_max(self):
        amount, tag, label = _raw_features()
        events = EVENTS + [
            {"id": "a", "day": 6, "amount": 7.0, "tag": "x", "converted": 1}]
        common = dict(key_fn=lambda r: r["id"], time_fn=lambda r: r["day"],
                      target_condition=lambda r: r["converted"] == 1,
                      drop_if_not_met=True)
        lo = DataReaders.conditional(
            events, time_stamp_to_keep="min", **common).read([amount, label])
        hi = DataReaders.conditional(
            events, time_stamp_to_keep="max", **common).read([amount, label])
        lo_amt = {r["key"]: r["amount"] for r in lo.to_rows()}["a"]
        hi_amt = {r["key"]: r["amount"] for r in hi.to_rows()}["a"]
        # min cutoff = day 6 (predictors: days 1,2); max = day 8 (adds day 6)
        assert lo_amt == 15.0
        assert hi_amt == 22.0

    def test_keep_unmatched(self):
        amount, _, label = _raw_features()
        reader = DataReaders.conditional(
            EVENTS, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"],
            target_condition=lambda r: r["converted"] == 1,
            drop_if_not_met=False)
        ds = reader.read([amount, label])
        rows = {r["key"]: r for r in ds.to_rows()}
        # unmatched key: all events are predictors, the response stays empty
        assert rows["b"]["amount"] == 52.0
        assert rows["b"]["converted"] is None


class TestJoinedReader:
    def test_left_join(self):
        left = DataReaders.simple(
            records=[{"id": "a", "age": 30}, {"id": "b", "age": 40}],
            key_fn=lambda r: r["id"])
        right = DataReaders.simple(
            records=[{"id": "a", "city": "sf"}],
            key_fn=lambda r: r["id"])
        ds = left.left_outer_join(right).read([])
        rows = {r["key"]: r for r in ds.to_rows()}
        assert rows["a"]["city"] == "sf"
        assert rows["b"]["city"] is None

    def test_inner_join(self):
        left = DataReaders.simple(
            records=[{"id": "a", "age": 30}, {"id": "b", "age": 40}],
            key_fn=lambda r: r["id"])
        right = DataReaders.simple(
            records=[{"id": "a", "city": "sf"}], key_fn=lambda r: r["id"])
        ds = left.inner_join(right).read([])
        assert {r["key"] for r in ds.to_rows()} == {"a"}

    def test_secondary_aggregation(self):
        left = DataReaders.simple(
            records=[{"id": "a", "age": 30}], key_fn=lambda r: r["id"])
        right = DataReaders.simple(
            records=[{"id": "a", "spend": 1.0}, {"id": "a", "spend": 2.0}],
            key_fn=lambda r: r["id"],
            schema={"spend": T.Real})
        joined = left.left_outer_join(right).with_secondary_aggregation()
        ds = joined.read([])
        rows = ds.to_rows()
        assert len(rows) == 1
        assert rows[0]["spend"] == 3.0  # Real default monoid = sum

    def test_outer_join_expands_right_only_children(self):
        left = DataReaders.simple(
            records=[{"id": "a", "age": 30}], key_fn=lambda r: r["id"])
        right = DataReaders.simple(
            records=[{"id": "z", "spend": 1.0}, {"id": "z", "spend": 2.0},
                     {"id": "z", "spend": 3.0}],
            key_fn=lambda r: r["id"])
        ds = left.outer_join(right).read([])
        zrows = [r for r in ds.to_rows() if r["key"] == "z"]
        assert sorted(r["spend"] for r in zrows) == [1.0, 2.0, 3.0]

    def test_join_of_two_aggregating_readers_keeps_ownership(self):
        # each aggregating side owns its feature set; the other side must not
        # shadow it with empty columns
        visits = [{"id": "a", "day": 1, "visits": 1.0},
                  {"id": "a", "day": 2, "visits": 1.0}]
        purchases = [{"id": "a", "day": 1, "spend": 5.0},
                     {"id": "a", "day": 3, "spend": 7.0}]
        nvisits = FeatureBuilder.Real("visits").from_column("visits").as_predictor()
        spend = FeatureBuilder.Real("spend").from_column("spend").as_predictor()
        left = DataReaders.aggregate(
            visits, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"],
            features=[nvisits])
        right = DataReaders.aggregate(
            purchases, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"],
            features=[spend])
        ds = left.left_outer_join(right).read([nvisits, spend])
        row = ds.to_rows()[0]
        assert row["visits"] == 2.0
        assert row["spend"] == 12.0
        assert ds.pre_extracted == {"visits", "spend"}

    def test_duplicate_rows_without_secondary(self):
        left = DataReaders.simple(
            records=[{"id": "a", "age": 30}], key_fn=lambda r: r["id"])
        right = DataReaders.simple(
            records=[{"id": "a", "spend": 1.0}, {"id": "a", "spend": 2.0}],
            key_fn=lambda r: r["id"])
        ds = left.left_outer_join(right).read([])
        assert len(ds.to_rows()) == 2  # one row per child match


class TestStreamingReader:
    def test_micro_batches(self):
        records = [{"x": float(i)} for i in range(10)]
        reader = DataReaders.stream(records=records, batch_size=4)
        batches = list(reader.stream())
        assert [len(b) for b in batches] == [4, 4, 2]
        assert reader.read().n_rows == 10

    def test_csv_stream(self, tmp_path):
        p = tmp_path / "s.csv"
        p.write_text("x,y\n1,2\n3,4\n5,6\n")
        reader = DataReaders.stream(csv_path=str(p), batch_size=2)
        batches = list(reader.stream())
        assert [len(b) for b in batches] == [2, 1]


class TestJoinDerivability:
    def test_single_aggregating_side_restricted_to_derivable(self):
        # ADVICE r1 (medium): an aggregating reader without features= joined
        # to a SimpleReader must only aggregate raw features derivable from
        # ITS records — the simple side's column must not be shadowed by a
        # garbage pre-extracted column
        events = [{"id": "a", "day": 1, "spend": 5.0},
                  {"id": "a", "day": 2, "spend": 7.0}]
        profiles = [{"id": "a", "age": 33.0}]
        spend = FeatureBuilder.Real("spend").from_column("spend").as_predictor()
        age = FeatureBuilder.Real("age").from_column("age").as_predictor()
        agg = DataReaders.aggregate(
            events, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"])
        simple = DataReaders.simple(records=profiles, key_fn=lambda r: r["id"])
        ds = agg.left_outer_join(simple).read([spend, age])
        row = ds.to_rows()[0]
        assert row["spend"] == 12.0   # aggregated by the event side
        assert row["age"] == 33.0     # supplied intact by the simple side
        assert "age" not in (ds.pre_extracted or set())

    def test_two_aggregating_readers_without_allowlists_raise(self):
        events = [{"id": "a", "day": 1, "spend": 5.0}]
        spend = FeatureBuilder.Real("spend").from_column("spend").as_predictor()
        a = DataReaders.aggregate(
            events, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"])
        b = DataReaders.aggregate(
            events, key_fn=lambda r: r["id"], time_fn=lambda r: r["day"])
        with pytest.raises(ValueError, match="allowlist"):
            a.inner_join(b).read([spend])
