"""perf/ learned cost model: fit/predict/persistence, the cold-start
contract (empty corpus → every consumer reproduces today's heuristics
bit for bit, regression-tested per call site), the pre-dispatch HBM
gate vs the OOM-halving fallback, residual recording (histogram +
goodput), journal facts harvesting, and params threading.

The suite runs with TRANSMOGRIFAI_PERF_MODEL=0 (conftest); tests that
exercise the model opt in per-test via the `perf_env` fixture, which
also isolates the corpus in tmp_path and resets the cached model."""

import json
import math
import os

import numpy as np
import pytest

from transmogrifai_tpu import perf
from transmogrifai_tpu.perf.smoke import synth_corpus


@pytest.fixture
def perf_env(tmp_path, monkeypatch):
    """Enable the perf model with an isolated corpus; restore after."""
    monkeypatch.setenv("TRANSMOGRIFAI_PERF_MODEL", "1")
    perf.set_params(perf.PerfModelParams(corpus_dir=str(tmp_path),
                                         min_rows=4))
    perf.set_model(None)
    yield tmp_path
    perf.set_model(None)
    perf.set_params(None)


def _warm_model(rows_by_target, min_rows=1):
    """A CostModel fitted on handcrafted rows per target."""
    model = perf.CostModel(min_rows=min_rows)
    for target, rows in rows_by_target.items():
        model.fit_target(target, rows)
    return model


def _block_rows(iters_values, scale=0.01, n_rows=240, n_cols=6, n_folds=2):
    """block_runtime rows whose value is proportional to max_iter."""
    rows = []
    for it in iters_values:
        for rep in range(3):
            feats = perf.block_features("logistic", (it, False), 2,
                                        n_rows, n_cols, n_folds)
            rows.append({"features": feats, "value": scale * it})
    return rows


# --------------------------------------------------------------------------- #
# model core                                                                  #
# --------------------------------------------------------------------------- #

class TestCostModel:
    def test_fit_recovers_multiplicative_law(self, perf_env):
        corpus = perf.get_corpus()
        synth_corpus(corpus)
        for target in ("block_runtime", "ingest", "serving_bucket", "hbm"):
            mape = perf.holdout_mape(corpus, target)
            assert mape is not None and mape < 0.35, (target, mape)

    def test_prediction_error_bars_bracket_value(self, perf_env):
        corpus = perf.get_corpus()
        synth_corpus(corpus)
        model = perf.fit_corpus(corpus)
        p = model.predict("block_runtime", {
            "n_configs": 4, "n_rows": 100_000, "n_cols": 50,
            "n_folds": 3, "dtype_bytes": 4, "fam_logistic": 1.0,
            "iters": 32})
        assert p is not None
        assert p.lo < p.value < p.hi
        assert p.n > 0
        # the law says 3e-8 * 4 * 32 * 1e5 = 0.384
        assert 0.25 < p.value < 0.55

    def test_save_load_roundtrip_bitwise(self, perf_env, tmp_path):
        corpus = perf.get_corpus()
        synth_corpus(corpus)
        model = perf.fit_corpus(corpus)
        path = str(tmp_path / "model.json")
        model.save(path)
        loaded = perf.CostModel.load(path)
        feats = {"n_configs": 2, "n_rows": 50_000, "n_cols": 50,
                 "n_folds": 3, "dtype_bytes": 4, "fam_logistic": 1.0,
                 "iters": 8}
        a = model.predict("block_runtime", feats)
        b = loaded.predict("block_runtime", feats)
        assert a.to_json() == b.to_json()

    def test_cold_targets_predict_none(self, perf_env):
        model = perf.CostModel()
        assert model.predict("block_runtime", {"n_configs": 1}) is None
        # below the min_rows floor is still cold
        model2 = perf.CostModel(min_rows=50)
        model2.fit_target("block_runtime", _block_rows((4, 8)))
        assert model2.predict("block_runtime",
                              {"n_configs": 2, "iters": 4}) is None

    def test_disabled_kill_switch(self, perf_env, monkeypatch):
        monkeypatch.setenv("TRANSMOGRIFAI_PERF_MODEL", "0")
        assert perf.get_model() is None
        assert perf.get_corpus() is None

    def test_corpus_torn_tail_tolerated(self, perf_env):
        corpus = perf.get_corpus()
        corpus.append("ingest", {"bytes_wire": 1e6}, 1.0)
        with open(corpus.path, "a") as fh:
            fh.write('{"target": "ingest", "features"')  # torn line
        corpus.append("ingest", {"bytes_wire": 2e6}, 2.0)
        assert len(corpus.rows("ingest")) == 2


# --------------------------------------------------------------------------- #
# consumer 1: scheduler ordering + width sizing                               #
# --------------------------------------------------------------------------- #

def _mk_blocks():
    from transmogrifai_tpu.parallel.scheduler import _Block
    # three logistic compile groups, equal config counts, different iters
    return [_Block(0, ("logistic", (4, False)), [0, 1]),
            _Block(0, ("logistic", (64, False)), [2, 3]),
            _Block(0, ("logistic", (16, False)), [4, 5])]


class TestSchedulerPlan:
    def _plan(self, blocks):
        from transmogrifai_tpu.parallel.scheduler import GridScheduler
        sched = GridScheduler(mesh=None)
        X = np.zeros((240, 6), np.float32)
        y = np.zeros(240, np.float32)
        folds = [(np.ones(240), np.ones(240))] * 2
        return sched._plan(blocks, X, y, folds)

    def test_cold_order_is_count_lpt(self, perf_env):
        perf.set_model(perf.CostModel())  # explicitly cold
        planned = self._plan(_mk_blocks())
        # today's heuristic: (-len, job, repr(key)) — ascending iters
        assert [b.key[1][0] for b in planned] == [16, 4, 64]
        assert all(b.pred_s is None for b in planned)

    def test_warm_order_is_predicted_lpt(self, perf_env):
        perf.set_model(_warm_model(
            {"block_runtime": _block_rows((4, 16, 64))}))
        planned = self._plan(_mk_blocks())
        assert [b.key[1][0] for b in planned] == [64, 16, 4]
        assert all(b.pred_s is not None for b in planned)
        preds = [b.pred_s for b in planned]
        assert preds == sorted(preds, reverse=True)

    def test_one_cold_block_degrades_whole_plan(self, perf_env):
        from transmogrifai_tpu.parallel.scheduler import _Block
        perf.set_model(_warm_model(
            {"block_runtime": _block_rows((4, 16, 64))}))
        blocks = _mk_blocks() + [_Block(1, ("generic", "abcd1234"), [0])]
        # hbm/generic unfitted targets are fine; but a block the model
        # CAN'T price (different... same target, still priced) — force
        # coldness via an unfitted model for contrast
        planned = self._plan(blocks)
        # generic block IS priced by the shared target (features degrade
        # to shape facts), so the plan stays warm — every block priced
        assert all(b.pred_s is not None for b in planned)

    def test_warm_oversize_block_splits_toward_target(self, perf_env,
                                                      monkeypatch):
        monkeypatch.setenv("TRANSMOGRIFAI_PERF_TARGET_BLOCK_S", "1.0")
        # value = 2.0 * iters seconds → the 64-iter 2-config block
        # predicts ~128s >> 2×1s target and must split into singles
        perf.set_model(_warm_model(
            {"block_runtime": _block_rows((4, 16, 64), scale=2.0)}))
        planned = self._plan(_mk_blocks())
        assert len(planned) == 6  # every 2-config block split
        assert all(len(b.idxs) == 1 for b in planned)
        # grid indices all survive exactly once
        assert sorted(i for b in planned for i in b.idxs) == list(range(6))


# --------------------------------------------------------------------------- #
# consumer 2: pre-dispatch HBM gate in _run_groups_resilient                  #
# --------------------------------------------------------------------------- #

def _oom_groups():
    """run_one that device-OOMs whenever more than one config is live."""
    calls = []

    def run_one(static, idxs):
        if len(idxs) > 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                               "allocating histogram buffers")
        calls.append(list(idxs))
    return {("grp",): [0, 1, 2, 3]}, run_one, calls


def _facts_cb(n_rows=1000):
    def facts(static, idxs):
        return perf.block_features("forest", (20, 32, False, 6),
                                   len(idxs), n_rows, 50, 3)
    return facts


def _run_resilient(groups, run_one, facts):
    from transmogrifai_tpu.obs import goodput
    from transmogrifai_tpu.obs.trace import TRACER
    from transmogrifai_tpu.parallel.sweep import _run_groups_resilient
    commits = []
    with TRACER.span("run:test-hbm", category="run", new_trace=True) as root:
        _run_groups_resilient(
            groups, run_one,
            commit=lambda idxs, s=None, f=None: commits.append(list(idxs)),
            family="forest", facts=facts)
    report = goodput.build_report(root, TRACER.trace_spans(root.trace_id))
    return commits, report


class TestHbmGate:
    def test_cold_pays_oom_redo_via_halving(self, perf_env):
        perf.set_model(perf.CostModel())
        groups, run_one, calls = _oom_groups()
        commits, report = _run_resilient(groups, run_one, _facts_cb())
        assert sorted(i for c in calls for i in c) == [0, 1, 2, 3]
        # the halving fallback burned real badput
        assert report.counts.get("oom_redos", 0) >= 1
        assert report.buckets["oom_redo_s"] > 0
        assert report.counts.get("hbm_preshrinks", 0) == 0

    def test_warm_gate_preshrinks_and_avoids_redo(self, perf_env):
        # hbm rows: value = 1e12 * n_configs → a 4-config block predicts
        # ~4 TB against the 4 GB default budget → pre-split to singles
        rows = []
        for k in (1, 2, 4, 8):
            feats = perf.block_features("forest", (20, 32, False, 6),
                                        k, 1000, 50, 3)
            rows.append({"features": feats, "value": 1e12 * k})
        perf.set_model(_warm_model({"hbm": rows}))
        groups, run_one, calls = _oom_groups()
        commits, report = _run_resilient(groups, run_one, _facts_cb())
        assert sorted(i for c in calls for i in c) == [0, 1, 2, 3]
        # the gate fired BEFORE dispatch: zero oom_redo badput paid
        assert report.counts.get("hbm_preshrinks", 0) == 1
        assert report.counts.get("oom_redos", 0) == 0
        assert report.buckets["oom_redo_s"] == 0.0

    def test_oom_becomes_negative_training_example(self, perf_env):
        perf.set_model(perf.CostModel())
        groups, run_one, _ = _oom_groups()
        _run_resilient(groups, run_one, _facts_cb())
        oom_rows = [r for r in perf.get_corpus().rows("hbm")
                    if r.get("oom")]
        assert oom_rows, "device OOM did not append an hbm training row"
        # inflated past the budget: the fit learns this shape is over
        assert oom_rows[0]["value"] >= perf.hbm_budget_bytes()

    def test_blocks_record_runtime_rows_even_cold(self, perf_env):
        perf.set_model(perf.CostModel())
        groups = {("g",): [0, 1]}
        commits, _ = _run_resilient(groups, lambda s, i: None, _facts_cb())
        assert commits == [[0, 1]]
        rows = perf.get_corpus().rows("block_runtime")
        assert len(rows) == 1
        assert rows[0]["features"]["n_configs"] == 2.0


# --------------------------------------------------------------------------- #
# consumer 3: upload workers/depth                                            #
# --------------------------------------------------------------------------- #

class _FakeStore:
    n_rows = 200_000
    n_features = 50


class TestUploadPlan:
    def test_cold_plan_is_todays_defaults(self, perf_env):
        from transmogrifai_tpu.data.pipeline import IngestStats
        from transmogrifai_tpu.parallel import bigdata as bd
        perf.set_model(perf.CostModel())
        stats = IngestStats()
        w, d = bd._resolve_upload_plan(_FakeStore(), 4096, None, None, stats)
        assert (w, d) == (bd.UPLOAD_WORKERS, bd.UPLOAD_DEPTH)
        assert stats.plan == "" and stats.predicted_wall_s == 0.0

    def test_explicit_values_always_win(self, perf_env):
        from transmogrifai_tpu.data.pipeline import IngestStats
        from transmogrifai_tpu.parallel import bigdata as bd
        perf.set_model(_warm_model({"ingest": self._ingest_rows()}))
        stats = IngestStats()
        w, d = bd._resolve_upload_plan(_FakeStore(), 4096, 3, 7, stats)
        assert (w, d) == (3, 7)

    @staticmethod
    def _ingest_rows():
        rows = []
        for workers in (1, 2, 4, 8):
            for depth in (1, 2, 4, 8):
                wall = 100.0 / math.sqrt(workers) + 10.0 / depth
                rows.append({"features": perf.ingest_features(
                    2e7, workers, depth, 49), "value": wall})
        return rows

    def test_warm_plan_picks_predicted_fastest(self, perf_env):
        from transmogrifai_tpu.data.pipeline import IngestStats
        from transmogrifai_tpu.parallel import bigdata as bd
        perf.set_model(_warm_model({"ingest": self._ingest_rows()}))
        stats = IngestStats()
        w, d = bd._resolve_upload_plan(_FakeStore(), 4096, None, None, stats)
        assert (w, d) == (8, 8)  # monotone-decreasing law
        assert stats.plan == "model" and stats.predicted_wall_s > 0

    def test_pipeline_records_ingest_row(self, perf_env):
        from transmogrifai_tpu.data.pipeline import (
            IngestStats, run_chunk_pipeline)
        perf.set_model(perf.CostModel())
        stats = IngestStats()

        def prep(i):
            stats.note_cast(0.0, 1000)
            return i

        run_chunk_pipeline(range(4), prep, lambda p: None,
                           workers=1, depth=1, stats=stats)
        rows = perf.get_corpus().rows("ingest")
        assert len(rows) == 1
        assert rows[0]["features"]["chunks"] == 4.0


# --------------------------------------------------------------------------- #
# consumer 4: serving ladder                                                  #
# --------------------------------------------------------------------------- #

class TestServingLadder:
    @staticmethod
    def _bucket_rows(per_row_s=2e-5, base=0.002):
        rows = []
        for b in (1, 2, 4, 8, 16, 32, 64):
            for _ in range(3):
                rows.append({"features": {"bucket": float(b)},
                             "value": base + per_row_s * b})
        return rows

    def test_cold_ladder_is_power_of_two(self, perf_env):
        from transmogrifai_tpu.serving.batcher import (
            bucket_ladder, derive_ladder)
        assert derive_ladder(64, 1, [1, 2, 3], None) == bucket_ladder(64, 1)
        # warm model but no sizes observed yet: also today's ladder
        model = _warm_model({"serving_bucket": self._bucket_rows()})
        assert derive_ladder(64, 1, [], model) == bucket_ladder(64, 1)
        # model without the serving target fitted: today's ladder
        assert derive_ladder(64, 1, [1, 2],
                             perf.CostModel()) == bucket_ladder(64, 1)

    def test_warm_flat_latency_collapses_rungs(self, perf_env):
        from transmogrifai_tpu.serving.batcher import (
            bucket_ladder, derive_ladder)
        # latency flat in bucket size → padding is free → rungs collapse
        model = _warm_model({"serving_bucket": self._bucket_rows(
            per_row_s=0.0, base=0.005)})
        ladder = derive_ladder(64, 1, [1, 2, 3, 40], model)
        assert ladder[-1] == 64  # the cap is always reachable
        assert len(ladder) < len(bucket_ladder(64, 1))

    def test_warm_steep_latency_keeps_traffic_rungs(self, perf_env):
        from transmogrifai_tpu.serving.batcher import derive_ladder
        model = _warm_model({"serving_bucket": self._bucket_rows(
            per_row_s=5e-3, base=1e-4)})
        sizes = [3] * 60 + [24] * 30 + [60] * 10
        ladder = derive_ladder(64, 1, sizes, model)
        assert ladder[-1] == 64
        assert len(ladder) >= 4  # steep cost: rungs survive
        # every request size has a rung within 2x (no huge padding)
        for s in (3, 24, 60):
            b = min(x for x in ladder if x >= s)
            assert b <= 2 * s + 8


# --------------------------------------------------------------------------- #
# residual recording + goodput + journal facts                                #
# --------------------------------------------------------------------------- #

class TestResiduals:
    def test_note_records_histogram_and_event(self, perf_env):
        from transmogrifai_tpu.obs import goodput
        from transmogrifai_tpu.obs.metrics import get_registry
        from transmogrifai_tpu.obs.trace import TRACER
        with TRACER.span("run:residual", category="run",
                         new_trace=True) as root:
            perf.note("block_runtime", {"n_configs": 1},
                      perf.Prediction(2.0, 1.5, 2.5, 10), 1.0)
        report = goodput.build_report(root, TRACER.trace_spans(root.trace_id))
        assert report.perf.get("predictions") == 1
        assert report.perf["mean_abs_rel_err"] == pytest.approx(1.0)
        assert report.perf["by_target"] == {"block_runtime": 1}
        reg = get_registry().to_json()
        assert "perf_model_abs_rel_err" in reg

    def test_sweep_journal_records_facts_and_harvests(self, perf_env,
                                                      tmp_path):
        import jax.numpy as jnp

        from transmogrifai_tpu.evaluators import (
            BinaryClassificationEvaluator)
        from transmogrifai_tpu.models import OpLogisticRegression
        from transmogrifai_tpu.parallel.sweep import run_sweep
        from transmogrifai_tpu.runtime.journal import SweepJournal
        from transmogrifai_tpu.selector.validators import OpCrossValidation
        from transmogrifai_tpu.stages.base import FitContext
        rng = np.random.default_rng(5)
        n = 120
        X = jnp.asarray(rng.normal(size=(n, 4)).astype(np.float32))
        y_np = (rng.normal(size=n) > 0).astype(np.float32)
        y = jnp.asarray(y_np)
        folds = OpCrossValidation(n_folds=2, seed=3).splits(y_np)
        path = str(tmp_path / "fam.journal")
        journal = SweepJournal(path, meta={"sig": "t"})
        est = OpLogisticRegression(max_iter=4)
        grids = [{"reg_param": r} for r in (0.01, 0.1)]
        run_sweep(est, grids, X, y, folds,
                  BinaryClassificationEvaluator(), FitContext(n_rows=n),
                  journal=journal)
        recs = journal.records()
        assert len(recs) == 2
        facts = recs[0]["facts"]
        assert facts is not None
        assert facts["fam_logistic"] == 1.0
        assert facts["n_configs"] == 2.0
        assert facts["n_cols"] == 4.0
        assert facts["block_s"] > 0
        assert "block_key" in facts
        # a RELOADED journal (resume path) still carries the facts
        reloaded = SweepJournal(path, meta={"sig": "t"})
        assert reloaded.records()[0]["facts"]["block_key"] == \
            facts["block_key"]
        # the live run already recorded this block (stamped with the
        # SAME block_key the journal carries): harvesting the run's own
        # journal must not duplicate it
        corpus = perf.get_corpus()
        live = corpus.rows("block_runtime")
        assert len(live) == 1 and live[0].get("block_key") == \
            facts["block_key"]
        assert perf.harvest_journal([path], corpus) == 0
        assert len(corpus.rows("block_runtime")) == 1
        # a corpus WITHOUT the live rows (another machine / lost rows)
        # harvests exactly one row per unique block — idempotently
        fresh = perf.CostCorpus(str(tmp_path / "fresh-corpus"))
        assert perf.harvest_journal([path], fresh) == 1
        assert perf.harvest_journal([path], fresh) == 0
        rows = fresh.rows("block_runtime")
        assert len(rows) == 1
        assert rows[0]["features"]["fam_logistic"] == 1.0
        assert "block_key" not in rows[0]["features"]
        assert rows[0]["block_key"] == facts["block_key"]


# --------------------------------------------------------------------------- #
# params threading                                                            #
# --------------------------------------------------------------------------- #

class TestParams:
    def test_opparams_roundtrip(self):
        from transmogrifai_tpu.workflow.params import OpParams
        p = OpParams.from_json({
            "perf_model": {"enabled": True, "corpus_dir": "/tmp/x",
                           "target_block_s": 12.5, "min_rows": 16}})
        assert p.perf_model.corpus_dir == "/tmp/x"
        assert p.perf_model.target_block_s == 12.5
        assert p.perf_model.min_rows == 16
        back = OpParams.from_json(p.to_json())
        assert back.perf_model.to_json() == p.perf_model.to_json()

    def test_params_scope_install_and_restore(self, monkeypatch):
        monkeypatch.setenv("TRANSMOGRIFAI_PERF_MODEL", "1")
        from transmogrifai_tpu.perf.params import (
            get_params, params_scope, resolved_corpus_dir)
        base = get_params()
        with params_scope({"corpus_dir": "/tmp/scope-test"}):
            assert resolved_corpus_dir() == "/tmp/scope-test"
        assert get_params() is base
        # None scope is a no-op (ambient params stay active)
        with params_scope(None):
            assert get_params() is base

    def test_env_kill_switch_beats_params(self, monkeypatch):
        monkeypatch.setenv("TRANSMOGRIFAI_PERF_MODEL", "0")
        from transmogrifai_tpu.perf.params import enabled
        perf.set_params(perf.PerfModelParams(enabled=True))
        try:
            assert not enabled()
        finally:
            perf.set_params(None)

    def test_serving_params_auto_ladder_roundtrip(self):
        from transmogrifai_tpu.workflow.params import ServingParams
        sp = ServingParams.from_json({"auto_ladder": True})
        assert sp.auto_ladder is True
        assert sp.to_config().auto_ladder is True
        assert ServingParams.from_json(sp.to_json()).auto_ladder is True


# --------------------------------------------------------------------------- #
# fleet corpus merge (replica shards, one directory)                          #
# --------------------------------------------------------------------------- #

class TestCorpusFleetMerge:
    def test_merge_total_order_breaks_int_second_ties(self, perf_env,
                                                      monkeypatch):
        """Replica shards on a fleet store carry identical int-second
        `ts` constantly; the (ts, replica, seq) total order must keep
        the merged view stable across fresh readers instead of leaving
        same-second interleaving to shard listing order."""
        import transmogrifai_tpu.perf.corpus as corpus_mod
        monkeypatch.setattr(corpus_mod.time, "time", lambda: 1700000000.5)
        ca = corpus_mod.CostCorpus(str(perf_env), replica="a")
        cb = corpus_mod.CostCorpus(str(perf_env), replica="b")
        # interleave appends so FILE order disagrees with replica order
        for i, c in enumerate([cb, ca, cb, ca, ca, cb]):
            assert c.append("block_runtime", {"x": 1.0}, float(i), tag=i)
        want = [1, 3, 4, 0, 2, 5]  # all of a's rows (by seq), then b's
        for _ in range(2):  # fresh readers agree with each other
            reader = corpus_mod.CostCorpus(str(perf_env))
            got = [r["tag"] for r in reader.rows("block_runtime")]
            assert got == want

    def test_harvest_after_live_recording_dedupes_across_shards(
            self, perf_env, monkeypatch):
        """A sweep host live-records its block row into ITS replica
        shard; harvesting that host's journal from ANOTHER replica must
        see the key through the merged view and skip it — even when
        every row carries the same int-second ts."""
        import transmogrifai_tpu.perf.corpus as corpus_mod
        from transmogrifai_tpu.perf.corpus import harvest_journal
        monkeypatch.setattr(corpus_mod.time, "time", lambda: 1700000000.5)
        live = corpus_mod.CostCorpus(str(perf_env), replica="h0")
        assert live.append("block_runtime", {"n_configs": 2.0}, 0.5,
                           source="live", block_key="bk1")
        journal = perf_env / "run.journal-wh0_0.jsonl"
        recs = [
            {"grid": {"i": 0}, "facts": {"block_key": "bk1",
                                         "block_s": 0.5, "n_configs": 2}},
            {"grid": {"i": 1}, "facts": {"block_key": "bk2",
                                         "block_s": 0.7, "n_configs": 2}},
        ]
        journal.write_text(
            "\n".join(json.dumps(r) for r in recs) + "\n", encoding="utf-8")
        harvester = corpus_mod.CostCorpus(str(perf_env), replica="hx")
        # bk1 is already live-recorded in h0's shard: only bk2 lands
        assert harvest_journal([str(journal)], corpus=harvester) == 1
        rows = harvester.rows("block_runtime")
        keys = [r.get("block_key") for r in rows]
        assert keys == ["bk1", "bk2"]
        assert rows[1]["source"] == "journal"
        # re-harvest is a no-op: both keys visible through the merge
        assert harvest_journal([str(journal)], corpus=harvester) == 0
        assert len(harvester.rows("block_runtime")) == 2
