"""Reference-shaped default sweep parity: elastic-net linear models, RF
minInfoGain/minInstancesPerNode axes, XGBoost early stopping
(`DefaultSelectorParams.scala:35-76`,
`BinaryClassificationModelSelector.scala:70-137`)."""

import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
from transmogrifai_tpu.models import (
    OpLinearRegression, OpLogisticRegression, OpRandomForestClassifier,
    OpXGBoostClassifier)
from transmogrifai_tpu.models.linear import fit_linreg, fit_linreg_enet
from transmogrifai_tpu.models.logistic import fit_logreg, fit_logreg_enet
from transmogrifai_tpu.models.trees import (
    bin_features, fit_gbt, fit_gbt_hosted, quantile_bin_edges)
from transmogrifai_tpu.parallel.sweep import run_sweep
from transmogrifai_tpu.selector.model_selector import (
    _default_binary_models, _default_multiclass_models,
    _default_regression_models)
from transmogrifai_tpu.stages.base import FitContext


@pytest.fixture(scope="module")
def binary_data():
    rng = np.random.default_rng(0)
    n, d = 1500, 10
    X = rng.normal(size=(n, d)).astype(np.float32)
    logits = 2 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    return X, y


def _folds(n, k=3):
    return [((np.arange(n) % k != f).astype(np.float32),
             (np.arange(n) % k == f).astype(np.float32)) for f in range(k)]


# --------------------------------------------------------------------------- #
# elastic net                                                                 #
# --------------------------------------------------------------------------- #

def test_enet_l1_zero_matches_lbfgs(binary_data):
    X, y = binary_data
    w = jnp.ones(len(y), jnp.float32)
    ref = fit_logreg(jnp.asarray(X), jnp.asarray(y), w, jnp.float32(0.01),
                     2, 100)
    fista = fit_logreg_enet(jnp.asarray(X), jnp.asarray(y), w,
                            jnp.float32(0.0), jnp.float32(0.01), 2, 400)
    np.testing.assert_allclose(np.asarray(fista["W"]), np.asarray(ref["W"]),
                               atol=5e-3)


def test_enet_l1_produces_sparsity(binary_data):
    X, y = binary_data
    w = jnp.ones(len(y), jnp.float32)
    p = fit_logreg_enet(jnp.asarray(X), jnp.asarray(y), w,
                        jnp.float32(0.05), jnp.float32(0.0), 2, 400)
    Wd = np.asarray(p["W"][:, 1] - p["W"][:, 0])
    nonzero = (np.abs(Wd) > 1e-6).sum()
    assert nonzero < len(Wd)          # some coefficients exactly zeroed
    assert nonzero >= 3               # ...but the true signal survives


def test_linreg_enet_matches_ridge_and_sparsifies():
    rng = np.random.default_rng(1)
    n, d = 800, 12
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.zeros(d); beta[:4] = [2.0, -1.0, 0.5, 1.5]
    y = (X @ beta + 0.3 + rng.normal(0, 0.05, n)).astype(np.float32)
    w = jnp.ones(n, jnp.float32)
    ridge = fit_linreg(jnp.asarray(X), jnp.asarray(y), w, jnp.float32(0.01))
    fista = fit_linreg_enet(jnp.asarray(X), jnp.asarray(y), w,
                            jnp.float32(0.0), jnp.float32(0.01))
    np.testing.assert_allclose(np.asarray(fista["beta"]),
                               np.asarray(ridge["beta"]), atol=2e-3)
    lasso = fit_linreg_enet(jnp.asarray(X), jnp.asarray(y), w,
                            jnp.float32(0.1), jnp.float32(0.0))
    b = np.asarray(lasso["beta"])
    assert (np.abs(b) > 1e-6).sum() <= 6  # noise columns zeroed


def test_lr_estimator_enet_param(binary_data):
    X, y = binary_data
    est = OpLogisticRegression(reg_param=0.05, elastic_net_param=0.5,
                               max_iter=50)
    ctx = FitContext(n_rows=len(y), seed=0)
    model = est.fit_arrays(jnp.asarray(X), jnp.asarray(y),
                           jnp.ones(len(y), jnp.float32), ctx)
    pred = model.predict_arrays(jnp.asarray(X))
    acc = float((np.asarray(pred["prediction"]) == y).mean())
    assert acc > 0.8
    # the L1 half of the penalty must zero at least the weakest coords
    Wd = np.abs(model.W[:, 1] - model.W[:, 0])
    assert (Wd < 1e-6).sum() >= 1


def test_lr_enet_sweep_grid(binary_data):
    X, y = binary_data
    est = OpLogisticRegression(max_iter=50)
    grids = [{"reg_param": r, "elastic_net_param": a}
             for a in (0.1, 0.5) for r in (0.001, 0.01, 0.1, 0.2)]
    m = run_sweep(est, grids, jnp.asarray(X), jnp.asarray(y),
                  _folds(len(y)), BinaryClassificationEvaluator(metric="AuPR"),
                  FitContext(n_rows=len(y), seed=0))
    m = np.asarray(m, dtype=float)
    assert m.shape == (8, 3)
    assert np.all(np.isfinite(m)) and np.all(m > 0.6)
    # heavier L1 (alpha .5, reg .2) must actually change the metric
    assert not np.allclose(m[0], m[7])


# --------------------------------------------------------------------------- #
# forest grid axes                                                            #
# --------------------------------------------------------------------------- #

def test_rf_min_info_gain_prunes_splits(binary_data):
    X, y = binary_data
    ctx = FitContext(n_rows=len(y), seed=0)
    w = jnp.ones(len(y), jnp.float32)
    loose = OpRandomForestClassifier(n_trees=5, max_depth=6,
                                     min_info_gain=0.0)
    tight = OpRandomForestClassifier(n_trees=5, max_depth=6,
                                     min_info_gain=0.3)
    m_loose = loose.fit_arrays(jnp.asarray(X), jnp.asarray(y), w, ctx)
    m_tight = tight.fit_arrays(jnp.asarray(X), jnp.asarray(y), w, ctx)
    # bin == n_bins means "no split": the tight threshold must prune more
    splits_loose = (m_loose.trees["bin"] < loose.max_bins).sum()
    splits_tight = (m_tight.trees["bin"] < tight.max_bins).sum()
    assert splits_tight < splits_loose


def test_rf_min_instances_per_node(binary_data):
    X, y = binary_data
    ctx = FitContext(n_rows=len(y), seed=0)
    w = jnp.ones(len(y), jnp.float32)
    many = OpRandomForestClassifier(n_trees=3, max_depth=8,
                                    min_instances_per_node=1.0)
    few = OpRandomForestClassifier(n_trees=3, max_depth=8,
                                   min_instances_per_node=200.0)
    s_many = (many.fit_arrays(jnp.asarray(X), jnp.asarray(y), w, ctx)
              .trees["bin"] < 32).sum()
    s_few = (few.fit_arrays(jnp.asarray(X), jnp.asarray(y), w, ctx)
             .trees["bin"] < 32).sum()
    assert s_few < s_many


@pytest.mark.slow
def test_rf_sweep_reference_grid(binary_data):
    X, y = binary_data
    est = OpRandomForestClassifier(n_trees=10)
    grids = [{"max_depth": d, "min_info_gain": g, "min_instances_per_node": m}
             for d in (3, 6) for g in (0.001, 0.1) for m in (10.0, 100.0)]
    m = run_sweep(est, grids, jnp.asarray(X), jnp.asarray(y),
                  _folds(len(y)), BinaryClassificationEvaluator(metric="AuPR"),
                  FitContext(n_rows=len(y), seed=0))
    m = np.asarray(m, dtype=float)
    assert m.shape == (8, 3) and np.all(np.isfinite(m))
    # the min_info_gain axis must differentiate configs
    assert not np.allclose(m[0].mean(), m[2].mean())


# --------------------------------------------------------------------------- #
# XGBoost early stopping                                                      #
# --------------------------------------------------------------------------- #

def _overfit_data():
    """Tiny noisy problem a deep 0.5-eta booster overfits within a few
    rounds — early stopping must freeze well before the round budget."""
    rng = np.random.default_rng(3)
    n, d = 400, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = ((X[:, 0] + rng.normal(0, 1.5, n)) > 0).astype(np.float32)
    return X, y


def _effective_rounds(trees) -> int:
    leaves = np.asarray(trees["leaf"])
    return int(np.any(np.abs(leaves.reshape(leaves.shape[0], -1)) > 0,
                      axis=1).sum())


def test_gbt_early_stopping_fewer_rounds():
    X, y = _overfit_data()
    n = len(y)
    Xb = bin_features(jnp.asarray(X), jnp.asarray(quantile_bin_edges(X, 16)))
    rng = np.random.default_rng(0)
    val = jnp.asarray((rng.uniform(size=n) < 0.3), dtype=jnp.float32)
    trees, _ = fit_gbt(Xb, jnp.asarray(y), 1.0 - val, 60, 5, 16,
                       jnp.float32(0.5), jnp.float32(1.0), "logistic",
                       seed=0, val_w=val, early_stopping_rounds=5)
    stopped = _effective_rounds(trees)
    trees_full, _ = fit_gbt(Xb, jnp.asarray(y), 1.0 - val, 60, 5, 16,
                            jnp.float32(0.5), jnp.float32(1.0), "logistic",
                            seed=0)
    assert _effective_rounds(trees_full) == 60
    assert stopped < 60


def test_gbt_hosted_early_stop_skips_dispatches():
    X, y = _overfit_data()
    n = len(y)
    Xb = bin_features(jnp.asarray(X), jnp.asarray(quantile_bin_edges(X, 16)))
    rng = np.random.default_rng(0)
    val = jnp.asarray((rng.uniform(size=n) < 0.3), dtype=jnp.float32)
    trees, _ = fit_gbt_hosted(Xb, jnp.asarray(y), 1.0 - val, 60, 5, 16,
                              jnp.float32(0.5), jnp.float32(1.0), "logistic",
                              seed=0, val_w=val, early_stopping_rounds=5,
                              rounds_per_dispatch=10)
    # the host loop truncates: fewer trees MATERIALIZED, not just zeroed
    assert np.asarray(trees["leaf"]).shape[0] < 60
    # and the materialized prefix matches the monolithic ES fit bitwise
    full, _ = fit_gbt(Xb, jnp.asarray(y), 1.0 - val, 60, 5, 16,
                      jnp.float32(0.5), jnp.float32(1.0), "logistic",
                      seed=0, val_w=val, early_stopping_rounds=5)
    k = np.asarray(trees["leaf"]).shape[0]
    np.testing.assert_array_equal(np.asarray(trees["leaf"]),
                                  np.asarray(full["leaf"])[:k])


@pytest.mark.slow
def test_xgb_sweep_es_matches_refit(binary_data):
    """The early-stopped sweep metric and a refit with the winning grid
    must describe the same algorithm: refit on the sweep's train fold
    reproduces the sweep's fold metric."""
    X, y = binary_data
    n = len(y)
    est = OpXGBoostClassifier(n_estimators=30, eta=0.3, max_depth=3,
                              early_stopping_rounds=5)
    grids = [{"min_child_weight": 1.0}]
    folds = _folds(n, 3)[:1]
    ev = BinaryClassificationEvaluator(metric="AuPR")
    ctx = FitContext(n_rows=n, seed=0)
    m = run_sweep(est, grids, jnp.asarray(X), jnp.asarray(y), folds, ev, ctx)
    tr, va = folds[0]
    # refit with the SWEEP's fold semantics: train rows weighted by the
    # fold mask, early-stop eval on the validation rows with the
    # estimator's eval metric (OpXGBoostClassifier defaults to the
    # reference's maximized aucpr)
    trees, margin = fit_gbt_hosted(
        bin_features(jnp.asarray(X),
                     jnp.asarray(quantile_bin_edges(X, est.max_bins))),
        jnp.asarray(y), jnp.asarray(tr), 30, 3, est.max_bins,
        jnp.float32(0.3), jnp.float32(1.0), "logistic", seed=0,
        val_w=jnp.asarray(va), early_stopping_rounds=5,
        eval_metric=est.eval_metric)
    from transmogrifai_tpu.models.trees import gbt_pred_from_margin
    from transmogrifai_tpu.data.columns import Column
    import transmogrifai_tpu.types as t
    pred = gbt_pred_from_margin(margin, "logistic")
    idx = va > 0.5
    pcol = Column(t.Prediction,
                  {k: np.asarray(v)[idx] for k, v in pred.items()})
    lcol = Column(t.RealNN, {"value": y[idx].astype(np.float64),
                             "mask": np.ones(int(idx.sum()), bool)})
    refit_metric = ev.metric_value(lcol, pcol)
    assert m[0][0] == pytest.approx(refit_metric, abs=1e-5)


# --------------------------------------------------------------------------- #
# default grid shapes                                                         #
# --------------------------------------------------------------------------- #

def test_default_grid_shapes_match_reference():
    binary = _default_binary_models()
    assert [len(g) for _, g in binary] == [8, 18, 2]       # 28 configs
    lr, rf, xgb = (e for e, _ in binary)
    assert lr.max_iter == 50
    assert rf.n_trees == 50
    assert xgb.n_estimators == 200 and xgb.learning_rate == 0.02
    assert xgb.max_depth == 10 and xgb.gamma == 0.8
    assert xgb.early_stopping_rounds == 20
    assert {g["min_child_weight"] for _, gs in binary[2:] for g in gs} \
        == {1.0, 10.0}

    multi = _default_multiclass_models()
    assert [len(g) for _, g in multi] == [8, 18]           # 26 configs

    reg = _default_regression_models()
    assert [len(g) for _, g in reg] == [8, 18, 18]         # 44 configs
    gbt = reg[2][0]
    assert gbt.n_estimators == 20 and gbt.learning_rate == 0.1


# --------------------------------------------------------------------------- #
# GLM link functions                                                          #
# --------------------------------------------------------------------------- #

class TestGLMLinks:
    @pytest.mark.parametrize("family,link", [
        ("gaussian", "identity"), ("gaussian", "log"),
        ("binomial", "logit"), ("binomial", "probit"),
        ("binomial", "cloglog"), ("poisson", "log"), ("poisson", "sqrt"),
        ("gamma", "log"), ("gamma", "inverse")])
    def test_link_fits(self, family, link):
        from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
        rng = np.random.default_rng(7)
        n = 500
        x = rng.uniform(0.2, 2.0, n).astype(np.float32)
        X = x[:, None]
        eta = 0.8 * x + 0.2
        if family == "binomial":
            mu = 1 / (1 + np.exp(-eta))
            y = (rng.uniform(size=n) < mu).astype(np.float32)
        elif family == "poisson":
            y = rng.poisson(np.exp(0.3 * x)).astype(np.float32)
        elif family == "gamma":
            y = rng.gamma(2.0, np.exp(0.3 * x) / 2.0).astype(np.float32) + 1e-3
        else:
            y = (eta + rng.normal(0, 0.1, n)).astype(np.float32)
        est = OpGeneralizedLinearRegression(family=family, link=link,
                                            max_iter=60)
        ctx = FitContext(n_rows=n, seed=0)
        model = est.fit_arrays(jnp.asarray(X), jnp.asarray(y),
                               jnp.ones(n, jnp.float32), ctx)
        pred = np.asarray(model.predict_arrays(jnp.asarray(X))["prediction"])
        assert np.all(np.isfinite(pred))
        # predictions live in the family's mean domain
        if family == "binomial":
            assert np.all((pred >= 0) & (pred <= 1))
            acc = ((pred > 0.5) == (y > 0.5)).mean()
            assert acc > 0.5
        elif family in ("poisson", "gamma"):
            assert np.all(pred >= 0)

    def test_invalid_link_rejected(self):
        from transmogrifai_tpu.models.glm import OpGeneralizedLinearRegression
        with pytest.raises(ValueError, match="invalid for family"):
            OpGeneralizedLinearRegression(family="binomial", link="log")

    def test_link_roundtrips_through_model_params(self):
        from transmogrifai_tpu.models.glm import GLMModel
        m = GLMModel(beta=[1.0], b=0.5, family="binomial", link="probit")
        p = m.get_params()
        assert p["link"] == "probit"
        m2 = GLMModel(**{k: v for k, v in p.items()})
        assert m2.link == "probit"
