"""Pipelined host→device ingest (`data/pipeline.py` + the rewired
upload builders in `parallel/bigdata.py`): serial-parity (bitwise),
bounded in-flight depth, worker-exception propagation, deadline
semantics, one-pass dual-representation builds, sharded placement, and
RunProfile ingest timers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from transmogrifai_tpu.data.columnar_store import synth_binary_store
from transmogrifai_tpu.data.pipeline import IngestStats, run_chunk_pipeline
from transmogrifai_tpu.models.trees import bin_features
from transmogrifai_tpu.parallel import bigdata as bd
from transmogrifai_tpu.utils.profiling import RunProfile


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("ingest") / "s1")
    return synth_binary_store(path, 5000, 12, seed=9, chunk_rows=1024)


def _serial_bf16(store):
    """The pre-pipeline reference build: full host read → bf16 cast →
    one transfer."""
    ref = np.asarray(store.chunk(0, store.n_rows))
    return np.asarray(jnp.asarray(ref, jnp.bfloat16))


def _serial_binned(store, edges):
    ref = np.asarray(store.chunk(0, store.n_rows))
    return np.asarray(bin_features(jnp.asarray(ref, jnp.float32),
                                   jnp.asarray(edges)).astype(jnp.int8))


# -- serial parity (bitwise) ------------------------------------------------ #

def test_device_matrix_bitwise_matches_serial(store):
    n = store.n_rows
    buf = bd.device_matrix(store, chunk_rows=1024, workers=3, depth=3)
    got = np.asarray(buf[:n])
    want = _serial_bf16(store)
    assert got.dtype == want.dtype
    assert got.tobytes() == want.tobytes()  # bitwise, not allclose
    assert float(jnp.abs(buf[n:].astype(jnp.float32)).sum()) == 0.0


def test_device_binned_bitwise_matches_serial(store):
    edges = store.quantile_edges(16, sample=5000)
    buf = bd.device_binned(store, edges, chunk_rows=1024, workers=3,
                           depth=2)
    np.testing.assert_array_equal(np.asarray(buf[:store.n_rows]),
                                  _serial_binned(store, edges))


def test_dual_build_matches_single_builders(store):
    """ONE store sweep must produce exactly the buffers the two separate
    pipelined builders produce (and therefore the serial references)."""
    n = store.n_rows
    edges = store.quantile_edges(16, sample=5000)
    X16, Xb, stats = bd.dual_device_matrices(
        store, edges, chunk_rows=1024, workers=2, depth=2,
        return_stats=True)
    assert np.asarray(X16[:n]).tobytes() == _serial_bf16(store).tobytes()
    np.testing.assert_array_equal(np.asarray(Xb[:n]),
                                  _serial_binned(store, edges))
    # ONE pass: wire bytes ≈ one padded f16 matrix, not two matrices
    n_pad = -(-n // 1024) * 1024
    assert stats.bytes_wire == n_pad * store.n_features * 2
    assert stats.chunks == -(-n // 1024)


# -- pipeline mechanics ----------------------------------------------------- #

def test_in_flight_depth_bounded():
    """The pipeline must never hold more than `depth` un-drained
    completion tokens — the depth bound is what back-pressures dispatch
    so deadlines track real transfer progress."""
    stats = IngestStats()
    live = {"n": 0, "max": 0}

    class Token:
        def __init__(self):
            live["n"] += 1
            live["max"] = max(live["max"], live["n"])

        def block_until_ready(self):
            live["n"] -= 1

    def prepare(i):
        stats.note_read(0.0, 8)
        stats.note_cast(0.0, 8)
        return i

    run_chunk_pipeline(range(32), prepare, lambda i: Token(),
                       workers=2, depth=3, stats=stats)
    assert stats.max_in_flight == 3         # reached, never exceeded
    assert live["max"] <= 3 + 1             # transient: new token pre-trim
    assert live["n"] == 0                   # fully drained on return
    assert stats.chunks == 32


def test_worker_exception_propagates():
    def prepare(i):
        if i == 5:
            raise ValueError("bad chunk")
        return i

    with pytest.raises(ValueError, match="bad chunk"):
        run_chunk_pipeline(range(16), prepare, lambda i: None,
                           workers=2, depth=2)


def test_upload_exception_propagates():
    def upload(i):
        if i == 3:
            raise RuntimeError("device boom")
        return None

    with pytest.raises(RuntimeError, match="device boom"):
        run_chunk_pipeline(range(8), lambda i: i, upload,
                           workers=2, depth=2)


def test_deadline_fires_on_elapsed(store):
    with pytest.raises(TimeoutError, match="deadline"):
        bd.device_matrix(store, chunk_rows=512, deadline_s=0.0)


def test_empty_item_stream():
    stats = run_chunk_pipeline([], lambda i: i, lambda i: None,
                               workers=2, depth=2)
    assert stats.chunks == 0 and stats.wall_s >= 0.0


def test_prepare_materializes_off_the_memmap(store):
    """Worker prepare must COPY the chunk out of the memmap: a lazy view
    would defer the page faults (the actual disk read) to the main
    thread's transfer, silently re-serializing the pipeline."""
    stats = IngestStats()
    prep = bd._chunk_prepare(store, 1024, store.dtype, stats)
    _, c = prep(0)
    assert not np.shares_memory(c, store._X)


# -- stats / profile -------------------------------------------------------- #

def test_stats_and_profile_recorded(store):
    prof = RunProfile(run_type="test")
    buf, stats = bd.device_matrix(store, chunk_rows=1024, profile=prof,
                                  return_stats=True)
    assert stats.chunks == -(-store.n_rows // 1024)
    assert stats.bytes_read > 0 and stats.bytes_wire > 0
    assert stats.wall_s > 0 and stats.gbps > 0
    assert 0.0 <= stats.overlap_frac <= 1.0
    assert stats.max_in_flight >= 1
    [phase] = [p for p in prof.phases if p.name == "device_matrix_upload"]
    for key in ("read_s", "cast_s", "upload_wait_s", "overlap_frac",
                "gbps", "chunks", "depth", "max_in_flight"):
        assert key in phase.extra, key
    assert phase.duration_s == pytest.approx(stats.wall_s)
    # the profile JSON round-trips the ingest extras
    dumped = prof.to_json()["phases"][0]
    assert dumped["overlap_frac"] == phase.extra["overlap_frac"]


def test_wire_dtype_narrows_on_host(tmp_path):
    """A WIDER-than-target store (f32 → bf16) must cast on the host so
    the wire carries 2 bytes/elem — and still matches the serial
    reference bitwise (single rounding step, same as jnp.asarray)."""
    from transmogrifai_tpu.data.columnar_store import ColumnarStore
    rng = np.random.default_rng(0)
    X = rng.normal(size=(700, 6)).astype(np.float32)
    w = ColumnarStore.create(str(tmp_path / "f32"), 700, 6, dtype="float32",
                             with_labels=False)
    w.write_chunk(0, X)
    st = w.close()
    buf, stats = bd.device_matrix(st, chunk_rows=256, return_stats=True)
    want = np.asarray(jnp.asarray(X, jnp.bfloat16))
    assert np.asarray(buf[:700]).tobytes() == want.tobytes()
    n_pad = -(-700 // 256) * 256
    assert stats.bytes_wire == n_pad * 6 * 2  # bf16 wire, not f32


# -- sharded placement ------------------------------------------------------ #

def test_sharded_upload_matches_unsharded(store):
    """Per-chunk `device_put(chunk, sharding)`: the sharded build must
    equal the single-device build and land with the requested sharding
    (multichip uploads spread across the mesh)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    sharding = NamedSharding(mesh, P("data", None))
    ref = bd.device_matrix(store, chunk_rows=1024)
    buf = bd.device_binned(store, store.quantile_edges(16, sample=5000),
                           chunk_rows=1024)
    got = bd.device_matrix(store, chunk_rows=1024, sharding=sharding)
    assert got.sharding.is_equivalent_to(sharding, got.ndim)
    assert np.asarray(got).tobytes() == np.asarray(ref).tobytes()
    gotb = bd.device_binned(store, store.quantile_edges(16, sample=5000),
                            chunk_rows=1024, sharding=sharding)
    np.testing.assert_array_equal(np.asarray(gotb), np.asarray(buf))
