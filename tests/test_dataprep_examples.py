"""Dataprep examples (VERDICT r3 #7): ports of the reference's canonical
event-time demos — `helloworld/.../dataprep/JoinsAndAggregates.scala` and
`ConditionalAggregation.scala` — asserting their documented output
semantics end to end through the aggregate/conditional/joined readers."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))


def _by_key(rows):
    return {r["key"]: r for r in rows}


def test_joins_and_aggregates_output():
    import op_joins_aggregates as ex
    rows = _by_key(ex.run())
    assert set(rows) == {"123", "456", "789"}
    # user 123 — the fully-populated row of the reference's output table:
    # 2 clicks the day before the 04-09-2017 cutoff, 1 send in the prior
    # week, 1 click the next day, ctr = 2 / (1 + 1)
    assert rows["123"]["numClicksYday"] == 2.0
    assert rows["123"]["numSendsLastWeek"] == 1.0
    assert rows["123"]["numClicksTomorrow"] == 1.0
    assert rows["123"]["ctr"] == 1.0
    # user 456: both clicks/sends fall at/after the cutoff → the response
    # folds 1 click tomorrow; the predictor folds are EMPTY and SumReal's
    # monoid zero is None (Numerics.scala:18), so they are missing, and
    # ctr (divide needs both sides) is missing with them
    assert rows["456"]["numClicksTomorrow"] == 1.0
    assert rows["456"]["numClicksYday"] is None
    assert rows["456"]["numSendsLastWeek"] is None
    assert rows["456"]["ctr"] is None
    # user 789: present only in the sends table — the left join keeps the
    # key, the clicks-side features are missing
    assert rows["789"]["numSendsLastWeek"] == 1.0
    assert rows["789"]["numClicksYday"] is None
    assert rows["789"]["numClicksTomorrow"] is None


def test_conditional_aggregation_output():
    import op_conditional_aggregation as ex
    rows = _by_key(ex.run())
    # the reference's documented output table, byte for byte (keys have
    # our fixture's domain): opq never hits the SaveBig landing page and
    # is dropped (dropIfTargetConditionNotMet=true)
    assert set(rows) == {"xyz@example.com", "abc@example.com",
                         "lmn@example.com"}
    assert rows["xyz@example.com"] == {
        "key": "xyz@example.com",
        "numVisitsWeekPrior": 3.0, "numPurchasesNextDay": 1.0}
    assert rows["lmn@example.com"] == {
        "key": "lmn@example.com",
        "numVisitsWeekPrior": 0.0, "numPurchasesNextDay": 1.0}
    assert rows["abc@example.com"] == {
        "key": "abc@example.com",
        "numVisitsWeekPrior": 1.0, "numPurchasesNextDay": 0.0}


def test_sum_realnn_zero_vs_sum_real():
    """The distinction the two examples hinge on (Numerics.scala:18-21)."""
    from transmogrifai_tpu.aggregators import sum_agg
    assert sum_agg("SumReal")([]) is None
    assert sum_agg("SumRealNN", zero=0.0)([]) == 0.0
