"""Feature type lattice tests (reference: features/src/test/.../types/*Test.scala)."""

import numpy as np
import pytest

import transmogrifai_tpu.types as t


def test_registry_has_all_major_types():
    names = set(t.all_feature_types())
    expected = {
        "Real", "RealNN", "Binary", "Integral", "Percent", "Currency", "Date",
        "DateTime", "Text", "Email", "Base64", "Phone", "ID", "URL", "TextArea",
        "PickList", "ComboBox", "Country", "State", "City", "PostalCode",
        "Street", "OPVector", "TextList", "DateList", "DateTimeList",
        "MultiPickList", "Geolocation", "TextMap", "EmailMap", "Base64Map",
        "PhoneMap", "IDMap", "URLMap", "TextAreaMap", "PickListMap",
        "ComboBoxMap", "CountryMap", "StateMap", "CityMap", "PostalCodeMap",
        "StreetMap", "GeolocationMap", "BinaryMap", "IntegralMap", "RealMap",
        "PercentMap", "CurrencyMap", "DateMap", "DateTimeMap",
        "MultiPickListMap", "NameStats", "Prediction",
    }
    missing = expected - names
    assert not missing, f"missing types: {missing}"


def test_real_nullability():
    assert t.Real(None).is_empty
    assert t.Real(1.5).value == 1.5
    assert t.Real(float("nan")).is_empty
    assert t.Real(1.5).is_nullable
    with pytest.raises(t.FeatureTypeError):
        t.RealNN(None)
    assert not t.RealNN(2.0).is_nullable


def test_numeric_coercions():
    assert t.Integral(3.0).value == 3 and isinstance(t.Integral(3).value, int)
    assert t.Binary(1).value is True
    assert t.Binary(None).is_empty
    assert t.Date(1577836800000).value == 1577836800000
    assert isinstance(t.Currency(2.5), t.Real)
    with pytest.raises(t.FeatureTypeError):
        t.Real("abc")


def test_equality_and_hash():
    assert t.Real(1.0) == t.Real(1.0)
    assert t.Real(1.0) != t.RealNN(1.0)  # type-strict equality
    assert hash(t.Text("a")) == hash(t.Text("a"))
    assert t.Text(None) == t.Text.empty()
    s = {t.PickList("a"), t.PickList("a"), t.PickList("b")}
    assert len(s) == 2


def test_email_accessors():
    e = t.Email("ada@lovelace.org")
    assert e.prefix == "ada" and e.domain == "lovelace.org"
    assert t.Email("notanemail").domain is None
    assert t.Email(None).prefix is None


def test_url_accessors():
    u = t.URL("https://example.com/path?q=1")
    assert u.domain == "example.com" and u.protocol == "https" and u.is_valid
    assert not t.URL("gopher://x.y").is_valid
    assert t.URL("example.com/p").domain == "example.com"


def test_lists_and_sets():
    assert t.TextList(["a", "b"]).value == ["a", "b"]
    assert t.TextList(None).is_empty and len(t.TextList([])) == 0
    with pytest.raises(t.FeatureTypeError):
        t.TextList([1])
    mpl = t.MultiPickList(["x", "y", "x"])
    assert mpl.value == frozenset({"x", "y"}) and len(mpl) == 2
    with pytest.raises(t.FeatureTypeError):
        t.MultiPickList("bare-string")
    assert t.DateList([1.0, 2]).value == [1, 2]


def test_geolocation():
    g = t.Geolocation([37.77, -122.42, 5.0])
    assert g.lat == 37.77 and g.lon == -122.42 and g.accuracy == 5.0
    assert t.Geolocation(None).is_empty
    with pytest.raises(t.FeatureTypeError):
        t.Geolocation([100.0, 0.0, 1.0])
    with pytest.raises(t.FeatureTypeError):
        t.Geolocation([1.0, 2.0])


def test_opvector():
    v = t.OPVector([1.0, 2.0, 3.0])
    assert len(v) == 3 and not v.is_empty
    assert v == t.OPVector(np.array([1, 2, 3]))
    assert t.OPVector(None).is_empty
    with pytest.raises(t.FeatureTypeError):
        t.OPVector([[1.0], [2.0]])


def test_maps():
    m = t.RealMap({"a": 1, "b": None})
    assert m["a"] == 1.0 and m["b"] is None
    assert t.TextMap(None).is_empty
    with pytest.raises(t.FeatureTypeError):
        t.RealMap({"a": "oops"})
    with pytest.raises(t.FeatureTypeError):
        t.TextMap({1: "a"})
    gm = t.GeolocationMap({"home": [1.0, 2.0, 3.0]})
    assert gm["home"] == [1.0, 2.0, 3.0]
    mm = t.MultiPickListMap({"k": ["a", "b"]})
    assert mm["k"] == frozenset({"a", "b"})


def test_prediction():
    p = t.Prediction.build(1.0, raw_prediction=[-0.3, 0.3], probability=[0.2, 0.8])
    assert p.prediction == 1.0
    assert p.probability == [0.2, 0.8]
    assert p.raw_prediction == [-0.3, 0.3]
    with pytest.raises(t.FeatureTypeError):
        t.Prediction({"probability_0": 0.1})  # missing 'prediction'
    with pytest.raises(t.FeatureTypeError):
        t.Prediction({"prediction": 1.0, "bogus": 2.0})


def test_traits():
    assert isinstance(t.PickList("a"), t.Categorical)
    assert isinstance(t.MultiPickList(["a"]), t.MultiResponse)
    assert isinstance(t.Country("US"), t.Location)
    assert isinstance(t.Prediction.build(0.0), t.NonNullable)


def test_uid():
    from transmogrifai_tpu.utils.uid import UID, from_string, reset
    reset()
    u1, u2 = UID("Stage"), UID("Stage")
    assert u1 != u2 and u1.startswith("Stage_")
    assert from_string(u1) == ("Stage", "000000000001")
    with pytest.raises(ValueError):
        from_string("nope")
