"""Dataset / Column tests (reference: readers CSV tests, FeatureTypeSparkConverter tests)."""

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.data import Column, Dataset
from transmogrifai_tpu.data.columns import scalar_to_float


CSV = """age,fare,survived,name,embarked
22,7.25,0,Braund,S
38,71.2833,1,Cumings,C
,8.05,0,Unknown,
26,,1,Heikkinen,S
"""


def test_csv_inference():
    ds = Dataset.from_csv_string(CSV)
    assert len(ds) == 4
    assert ds.schema["age"] is t.Integral
    assert ds.schema["fare"] is t.Real
    assert ds.schema["survived"] is t.Integral  # 0/1 ints, like Spark CSV infer
    assert ds.schema["name"] is t.Text
    # numeric columns use typed float64 storage with NaN for missing
    assert np.isnan(ds.column("age")[2])
    assert np.isnan(ds.column("fare")[3])
    assert ds.column("embarked")[2] is None  # text stays object/None
    assert ds.column("survived")[1] == 1
    assert ds.to_rows()[2]["age"] is None  # row view restores None
    bools = Dataset.from_csv_string("flag\ntrue\nfalse\n\n")
    assert bools.schema["flag"] is t.Binary
    assert bools.column("flag")[0] == 1.0 and np.isnan(bools.column("flag")[2])
    assert bools.to_rows()[0]["flag"] == 1.0 and bools.to_rows()[2]["flag"] is None


def test_csv_explicit_schema():
    ds = Dataset.from_csv_string(CSV, schema={"survived": t.Integral, "embarked": t.PickList})
    assert ds.schema["survived"] is t.Integral
    assert ds.column("survived")[1] == 1
    assert ds.schema["embarked"] is t.PickList


def test_from_rows_and_take():
    ds = Dataset.from_rows([
        {"x": 1.0, "s": "a"}, {"x": None, "s": "b"}, {"x": 3.0, "s": None}])
    assert ds.schema["x"] is t.Real and ds.schema["s"] is t.Text
    sub = ds.take(np.array([0, 2]))
    assert len(sub) == 2 and sub.column("x")[1] == 3.0


def test_scalar_column():
    c = Column.from_values(t.Real, [1.0, None, 3.5])
    assert len(c) == 3
    assert c.kind == "scalar"
    np.testing.assert_array_equal(c.data["mask"], [True, False, True])
    dv = c.device_value()
    assert dv["value"].dtype == np.float32
    np.testing.assert_allclose(dv["value"], [1.0, 0.0, 3.5])
    vals = c.to_values()
    assert vals[1].is_empty and vals[2].value == 3.5
    f = scalar_to_float(c)
    assert np.isnan(f[1]) and f[0] == 1.0


def test_integral_column_keeps_int64():
    ms = 1_577_836_800_123
    c = Column.from_values(t.DateTime, [ms, None])
    assert c.data["value"].dtype == np.int64
    assert c.data["value"][0] == ms  # no float32 precision loss on host


def test_text_and_collection_columns():
    c = Column.from_values(t.PickList, ["a", None, "b"])
    assert c.kind == "text" and c.data[1] is None
    lc = Column.from_values(t.TextList, [["x"], [], None])
    assert lc.kind == "list" and lc.data[1] is None and lc.data[2] is None
    mc = Column.from_values(t.RealMap, [{"a": 1.0}, {}])
    assert mc.kind == "map" and mc.data[1] is None


def test_vector_column():
    c = Column.from_values(t.OPVector, [[1, 2], [3, 4]])
    assert c.kind == "vector" and c.width == 2
    np.testing.assert_allclose(c.device_value(), [[1, 2], [3, 4]])


def test_prediction_column_roundtrip():
    p0 = t.Prediction.build(1.0, raw_prediction=[-1, 1], probability=[0.3, 0.7])
    p1 = t.Prediction.build(0.0, raw_prediction=[2, -2], probability=[0.9, 0.1])
    c = Column.from_values(t.Prediction, [p0, p1])
    assert c.kind == "prediction"
    vals = c.to_values()
    assert vals[0].probability == [0.3, 0.7]
    assert vals[1].prediction == 0.0


def test_column_take():
    c = Column.from_values(t.Real, [1.0, None, 3.0, 4.0])
    s = c.take(np.array([0, 3]))
    assert len(s) == 2
    np.testing.assert_array_equal(s.data["mask"], [True, True])
