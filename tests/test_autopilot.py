"""Serving autopilot (PR 19): the SLO-burn-driven controller.

Controller-logic tests drive `Autopilot.tick(now=...)` with a fake
clock and a scripted burn signal against a fake fleet — hysteresis,
anti-flap, release-hold, and sensing-gap behavior are pure control-law
properties and must not need a trained model. Integration tests (the
re-armable rebucket shot, post-rebucket rollback warmth, drain-derived
Retry-After, predictive-vs-observed admission equivalence) use real
services/routers. The lint section covers L022 (actuation calls
outside the controller must emit a flight-recorder event)."""

import math
from types import SimpleNamespace

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu import perf
from transmogrifai_tpu.analysis import lint as L
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.obs.metrics import MetricsRegistry
from transmogrifai_tpu.perf.model import CostModel
from transmogrifai_tpu.serving import ScoreError
from transmogrifai_tpu.serving.autopilot import Autopilot, AutopilotParams
from transmogrifai_tpu.serving.router import Router, TenantPolicy
from transmogrifai_tpu.workflow import Workflow


# --------------------------------------------------------------------- #
# fakes: a fleet the controller can actuate without trained models      #
# --------------------------------------------------------------------- #

class _FakeBatcher:
    def __init__(self, depth=0):
        self.queue_depth = depth

    def depth(self):
        return self.queue_depth


class _FakeService:
    def __init__(self, depth=0, max_batch=4, deadline_ms=300.0,
                 armed=False):
        self.ladder = [1, 2, 4]
        self._batcher = _FakeBatcher(depth)
        self.config = SimpleNamespace(max_batch=max_batch,
                                      default_deadline_ms=deadline_ms)
        self.rearms = 0
        self._armed = armed

    def rearm_auto_rebucket(self):
        self.rearms += 1
        return True


class _FakeFleet:
    def __init__(self, members=None):
        self.registry = MetricsRegistry()
        self.router = Router(tenants={"low": TenantPolicy(priority=0),
                                      "hi": TenantPolicy(priority=1)},
                             registry=self.registry)
        self.slo_engine = None
        self.members = members if members is not None \
            else {"a": _FakeService()}
        self.fidelity = {}
        self.added = []
        self.removed = []

    def _live_services(self):
        return dict(self.members)

    def resolve_model(self, name):
        return self.fidelity.get(name, name)

    def set_fidelity_route(self, model, target):
        if target is None:
            self.fidelity.pop(model, None)
        else:
            self.fidelity[model] = target

    def add_model(self, name, path, overrides=None):
        self.added.append(name)
        self.members[name] = _FakeService()

    def remove_model(self, name):
        self.removed.append(name)
        self.members.pop(name, None)


def _pilot(burns, fleet=None, **params):
    """An Autopilot whose burn signal replays `burns` (a list; the last
    value repeats forever; None = sensing gap)."""
    defaults = dict(period_s=0.05, engage_burn=1.0, release_burn=0.5,
                    min_dwell_s=1.0, release_hold_s=0.0,
                    rebucket_cooldown_s=0.0)
    defaults.update(params)
    ap = Autopilot(fleet or _FakeFleet(),
                   AutopilotParams(**defaults))
    script = list(burns)

    def scripted():
        b = script.pop(0) if len(script) > 1 else script[0]
        return b, ({"slo": "x", "window": "w"} if b is not None else None)

    ap.burn_signal = scripted
    return ap


# --------------------------------------------------------------------- #
# params                                                                #
# --------------------------------------------------------------------- #

def test_params_validation():
    for bad in (dict(period_s=0.0),
                dict(engage_burn=0.5, release_burn=0.5),
                dict(engage_burn=0.4, release_burn=0.5),
                dict(release_burn=-0.1, engage_burn=0.5),
                dict(min_dwell_s=-1.0),
                dict(release_hold_s=-0.5),
                dict(rebucket_cooldown_s=-1.0),
                dict(admission_headroom=0.0),
                dict(spare={"name": "s"}),
                dict(spare="nope")):
        with pytest.raises(ValueError):
            AutopilotParams(**bad)


def test_params_json_roundtrip():
    p = AutopilotParams(period_s=0.1, engage_burn=2.0, release_burn=0.25,
                        release_hold_s=1.5, fidelity={"a": "a8"},
                        spare={"name": "s", "path": "/p"})
    q = AutopilotParams.from_json(p.to_json())
    assert q.to_json() == p.to_json()
    # unknown keys are ignored, absent ones default
    assert AutopilotParams.from_json({"bogus": 1}).engage_burn == 1.0


def test_ladder_skips_unconfigured_rungs():
    assert _pilot([0.0]).ladder == ("rebucket", "admission")
    full = _pilot([0.0], fidelity={"a": "a8"},
                  spare={"name": "s", "path": "/p"})
    assert full.ladder == ("rebucket", "fidelity", "admission", "spare")


# --------------------------------------------------------------------- #
# control law: hysteresis, anti-flap, release hold, sensing gaps        #
# --------------------------------------------------------------------- #

def test_healthy_fleet_makes_zero_actuations():
    ap = _pilot([0.0])
    for i in range(100):
        st = ap.tick(now=float(i))
    assert st["rung"] == 0 and st["actuations"] == 0


def test_one_rung_per_dwell_window():
    ap = _pilot([5.0], min_dwell_s=1.0)
    # dwell counts from construction too: nothing before t=1.0
    assert ap.tick(now=0.5)["rung"] == 0
    rungs = [ap.tick(now=1.0 + i * 0.1)["rung"] for i in range(31)]
    # first climb at the dwell boundary, one rung per dwell second
    assert rungs[0] == 1
    assert max(rungs[:10]) == 1 and rungs[10] == 2
    assert rungs[-1] == 2  # ladder exhausted (rebucket, admission)


def test_boundary_oscillation_cannot_flap_within_a_dwell_window():
    """The anti-flap acceptance: burn alternating ACROSS both
    thresholds every tick still produces at most one transition per
    dwell window (and the hysteresis band alone — oscillation between
    the thresholds — produces none)."""
    burns = [5.0 if i % 2 == 0 else 0.0 for i in range(400)]
    ap = _pilot(burns + [0.0], min_dwell_s=1.0)
    transitions, last = 0, 0
    for i in range(400):
        rung = ap.tick(now=1.0 + i * 0.01)["rung"]  # 4 dwell windows
        transitions += int(rung != last)
        last = rung
    assert 1 <= transitions <= 4
    # burn wandering INSIDE the band (release < burn < engage) after an
    # engage: no transitions at all, however long it wanders
    ap2 = _pilot([5.0] + [0.7] * 200 + [0.7], min_dwell_s=0.1)
    assert ap2.tick(now=1.0)["rung"] == 1
    for i in range(2, 200):
        assert ap2.tick(now=float(i))["rung"] == 1
    assert ap2.status()["actuations"] == 1


def test_release_requires_sustained_health():
    """One healthy blip shorter than release_hold_s must not walk a
    cure back; a sustained streak releases."""
    ap = _pilot([5.0, 5.0,                      # engage at t=0, 0.1
                 0.0, 0.0, 0.0,                 # blip: 3 ticks below
                 5.0,                           # storm back — streak reset
                 0.0],                          # then below forever
                min_dwell_s=0.0, release_hold_s=1.0)
    assert ap.tick(now=0.0)["rung"] == 1
    assert ap.tick(now=0.1)["rung"] == 2
    for now in (0.2, 0.5, 0.8):                 # 0.6s streak < 1.0s hold
        assert ap.tick(now=now)["rung"] == 2
    assert ap.tick(now=0.9)["rung"] == 2        # burn back up
    for now in (1.0, 1.5, 1.9):                 # new streak, still < hold
        assert ap.tick(now=now)["rung"] == 2
    assert ap.tick(now=2.1)["rung"] == 1        # 1.1s streak: release
    assert ap.tick(now=3.2)["rung"] == 0


def test_sensing_gap_holds_state():
    """A burn signal of None (engine starved under the very overload
    being damped, or windows spanning no traffic) is NOT health: the
    rung holds, and the gap breaks any release streak."""
    ap = _pilot([5.0, None],
                min_dwell_s=0.0, release_hold_s=0.5)
    assert ap.tick(now=0.0)["rung"] == 1
    for i in range(1, 50):                      # gap: hold forever
        assert ap.tick(now=i * 1.0)["rung"] == 1
    ap2 = _pilot([5.0] + [0.0, None] * 100 + [None],
                 min_dwell_s=0.0, release_hold_s=0.5)
    assert ap2.tick(now=0.0)["rung"] == 1
    for i in range(1, 100):                     # every streak gap-broken
        assert ap2.tick(now=i * 1.0)["rung"] == 1


def test_fresh_fleet_with_no_slo_engine_never_engages():
    ap = Autopilot(_FakeFleet(), AutopilotParams(min_dwell_s=0.0))
    for i in range(10):
        st = ap.tick(now=float(i))
    assert st["rung"] == 0 and st["actuations"] == 0
    assert ap.burn_signal() == (None, None)


# --------------------------------------------------------------------- #
# actuations against the fake fleet                                     #
# --------------------------------------------------------------------- #

def test_full_ladder_engages_and_releases_in_order():
    fleet = _FakeFleet(members={"a": _FakeService(),
                                "a8": _FakeService()})
    ap = _pilot([5.0] * 4 + [0.0], fleet=fleet, min_dwell_s=0.0,
                fidelity={"a": "a8"},
                spare={"name": "sp", "path": "/p"})
    for i in range(4):
        ap.tick(now=i * 1.0)
    assert ap.status()["engaged"] == ["rebucket", "fidelity",
                                      "admission", "spare"]
    assert fleet.fidelity == {"a": "a8"}
    assert fleet.added == ["sp"]
    assert fleet.members["a"].rearms == 1
    for i in range(4, 9):
        ap.tick(now=i * 1.0)
    assert ap.status()["rung"] == 0
    assert fleet.fidelity == {}
    assert fleet.removed == ["sp"]
    assert fleet.router.pressure("a") == 0.0
    # release re-armed the rebucket shot once more (recovered traffic)
    assert fleet.members["a"].rearms == 2


def test_controller_rebucket_cooldown():
    fleet = _FakeFleet()
    ap = _pilot([5.0, 0.0, 5.0, 0.0],
                fleet=fleet, min_dwell_s=0.0, rebucket_cooldown_s=10.0)
    ap.tick(now=0.0)   # engage: re-arms
    ap.tick(now=1.0)   # release: within cooldown — skipped
    ap.tick(now=2.0)   # engage again: still within cooldown
    assert fleet.members["a"].rearms == 1
    ap.tick(now=3.0)   # release
    ap2 = _pilot([5.0], fleet=fleet, min_dwell_s=0.0,
                 rebucket_cooldown_s=0.0)
    ap2.tick(now=100.0)
    assert fleet.members["a"].rearms == 2


def test_predictive_pressure_from_warm_model_and_fidelity_resolution(
        monkeypatch):
    """Warm model + deep queue -> pressure 1.0 under the primary NAME;
    after a fidelity flip the prediction reads the RESOLVED member's
    queue while the pressure key stays the logical name."""
    monkeypatch.setenv("TRANSMOGRIFAI_PERF_MODEL", "1")
    m = CostModel(min_rows=8)
    for _ in range(12):
        for b in (1, 2, 4):
            m.observe("serving_bucket", {"bucket": float(b)}, 0.05 * b)
    perf.set_model(m)
    try:
        fleet = _FakeFleet(members={"a": _FakeService(depth=12),
                                    "a8": _FakeService(depth=0)})
        ap = _pilot([5.0], fleet=fleet, min_dwell_s=0.0,
                    fidelity={"a": "a8"})
        for i in range(3):  # rebucket, fidelity, admission
            ap.tick(now=float(i))
        # 12 rows / bucket 4 = 3 batches x 0.2s = 0.6s vs 0.3s budget
        # ... but the flip moved traffic to a8 whose queue is EMPTY
        assert fleet.fidelity == {"a": "a8"}
        assert fleet.router.pressure("a") < 1.0
        # no pressure is ever written against the fidelity target
        assert fleet.router.pressure("a8") == 0.0
        # without the flip, the deep queue saturates the pressure
        fleet2 = _FakeFleet(members={"a": _FakeService(depth=12)})
        ap2 = _pilot([5.0], fleet=fleet2, min_dwell_s=0.0)
        ap2.tick(now=0.0)
        ap2.tick(now=1.0)
        assert fleet2.router.pressure("a") == 1.0
    finally:
        perf.set_model(None)


def test_cold_model_pressure_is_bit_identical_to_observed_shedding():
    """The predictive-admission rung with a COLD model must not change
    admission at all: pressure stays 0 and a router given the same
    request sequence makes identical decisions."""
    perf.set_model(None)
    fleet = _FakeFleet(members={"a": _FakeService(depth=12)})
    ap = _pilot([5.0], fleet=fleet, min_dwell_s=0.0)
    ap.tick(now=0.0)
    ap.tick(now=1.0)   # admission rung engaged
    assert "admission" in ap.status()["engaged"]
    assert fleet.router.pressure("a") == 0.0

    def decisions(router):
        out = []
        for frac in (0.0, 0.3, 0.55, 0.8, 1.0):
            try:
                router.admit("low", 1, frac, model="a")
                out.append("ok")
            except ScoreError as e:
                out.append((e.code, e.retry_after_s))
        return out

    tenants = {"low": TenantPolicy(priority=0),
               "hi": TenantPolicy(priority=1)}
    assert decisions(fleet.router) == decisions(Router(tenants=tenants))


def test_actuation_failures_do_not_kill_the_controller():
    fleet = _FakeFleet()
    fleet.set_fidelity_route = None  # not callable: actuation raises
    ap = _pilot([5.0], fleet=fleet, min_dwell_s=0.0,
                fidelity={"a": "a8"})
    for i in range(3):
        ap.tick(now=float(i))
    assert ap.status()["rung"] == 3  # ladder advanced despite the error


# --------------------------------------------------------------------- #
# integration: real service — re-armable rebucket, drain Retry-After    #
# --------------------------------------------------------------------- #

def _train_small(n=120, seed=3):
    rng = np.random.default_rng(seed)
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    y = ((x1 + 0.5 * x2) > 0).astype(np.float64)
    ds = Dataset({"x1": x1, "x2": x2, "y": y},
                 {"x1": t.Real, "x2": t.Real, "y": t.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    from transmogrifai_tpu.automl import transmogrify
    vec = transmogrify(preds)
    pred = OpLogisticRegression(max_iter=30).set_input(label, vec) \
        .get_output()
    return Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train()


ROW = {"x1": 0.4, "x2": -0.2}


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    from transmogrifai_tpu.serving import ScoringService, ServingConfig
    base = tmp_path_factory.mktemp("autopilot-svc")
    _train_small().save(str(base / "m"))
    _train_small(seed=5).save(str(base / "m_v2"))
    svc = ScoringService.from_path(
        str(base / "m"),
        config=ServingConfig(max_batch=8, batch_wait_ms=1.0,
                             auto_ladder=True)).start()
    yield svc, str(base / "m_v2")
    svc.stop()


def test_rearm_auto_rebucket_is_a_real_second_shot(served):
    svc, _ = served
    # nothing to re-arm while the organic one-shot is still armed
    assert svc.rearm_auto_rebucket() is False
    svc._auto_done = True           # the organic shot has landed
    assert svc.rearm_auto_rebucket() is True
    assert svc._auto_done is False  # armed again
    assert svc._auto_next > svc._auto_seen
    assert svc.rearm_auto_rebucket() is False  # idempotent until it fires


def test_rebucket_warms_every_resident_version_for_rollback(served,
                                                            monkeypatch):
    """The post-rebucket rollback regression: a rebucket that adds
    rungs AOT-warms them on the DEMOTED version too, so rollback()
    stays 'already warm — no compile' (zero new traces)."""
    from transmogrifai_tpu.analysis.retrace import MONITOR
    svc, v2 = served
    svc.reload(v2)  # two resident versions
    target = [1, 8] if 2 in svc.ladder else [1, 2, 8]
    monkeypatch.setattr(svc, "suggest_ladder", lambda: list(target))
    out = svc.rebucket()
    assert out["status"] == "rebucketed"
    before = MONITOR.snapshot()
    svc.rollback()
    assert svc.score([dict(ROW)]).n_rows == 1
    for b in target:
        rows = [dict(ROW) for _ in range(max(1, b - 1))]
        assert svc.score(rows).n_rows == len(rows)
    assert MONITOR.delta(before) == {}, \
        "rollback after rebucket recompiled — demoted version was not " \
        "warmed with the new rungs"


def test_retry_after_is_drain_derived_when_warm_and_constant_cold():
    """Satellite 2: the shed backoff hint is the perf model's predicted
    queue-drain time (clamped) when warm; the cold fallback is the
    constant observed-pressure heuristic."""
    r = Router(tenants={"low": TenantPolicy(priority=0),
                        "hi": TenantPolicy(priority=1)})
    with pytest.raises(ScoreError) as cold:
        r.admit("low", 1, 0.9, model="a")
    assert cold.value.retry_after_s == 0.9  # eff_frac heuristic
    with pytest.raises(ScoreError) as warm:
        r.admit("low", 1, 0.9, model="a", drain_s=4.2)
    assert warm.value.retry_after_s == 4.2
    with pytest.raises(ScoreError) as clamped:
        r.admit("low", 1, 0.9, model="a", drain_s=1e6)
    assert clamped.value.retry_after_s == 30.0


def test_predicted_drain_s_warm_vs_cold(served, monkeypatch):
    svc, _ = served
    perf.set_model(None)
    assert svc.predicted_drain_s() is None  # cold: no prediction
    monkeypatch.setenv("TRANSMOGRIFAI_PERF_MODEL", "1")
    m = CostModel(min_rows=8)
    for _ in range(12):
        for b in (1, 2, 4, 8):
            m.observe("serving_bucket", {"bucket": float(b)}, 0.05 * b)
    perf.set_model(m)
    try:
        d = svc.predicted_drain_s()
        assert d is not None and 0.1 <= d <= 30.0
    finally:
        perf.set_model(None)


def test_shed_reason_predictive_vs_observed():
    r = Router(tenants={"low": TenantPolicy(priority=0),
                        "hi": TenantPolicy(priority=1)})
    r.set_pressure("a", 1.0)  # autopilot-ok: unit test
    with pytest.raises(ScoreError):
        r.admit("low", 1, 0.0, model="a")  # observed queue EMPTY
    with pytest.raises(ScoreError):
        r.admit("low", 1, 0.9, model="b")  # no pressure: observed shed
    shed = {}
    for s in r.registry.to_json()["fleet_shed_total"]["series"]:
        shed[s["labels"]["reason"]] = s["value"]
    assert shed.get("shed_predictive") == 1
    assert shed.get("shed_low_priority") == 1
    r.set_pressure("a", 0.0)  # autopilot-ok: unit test
    assert r.pressure("a") == 0.0
    assert r.admit("low", 1, 0.0, model="a") == "low"


# --------------------------------------------------------------------- #
# config plumbing                                                       #
# --------------------------------------------------------------------- #

def test_serving_params_carry_autopilot_into_fleet_config():
    from transmogrifai_tpu.workflow.params import ServingParams
    sp = ServingParams.from_json(
        {"fleet": {"models": {"m": "/tmp/m"}},
         "autopilot": {"engage_burn": 2.0, "release_burn": 0.5}})
    cfg = sp.to_fleet_config()
    assert cfg.autopilot == {"engage_burn": 2.0, "release_burn": 0.5}
    p = AutopilotParams.from_json(cfg.autopilot)
    assert p.engage_burn == 2.0 and p.release_burn == 0.5


# --------------------------------------------------------------------- #
# lint: L022 unlogged actuations                                        #
# --------------------------------------------------------------------- #

_L022_SRC = '''
def operator_flip(fleet):
    fleet.set_fidelity_route("a", "a_int8")

def pressure_write(router):
    router.set_pressure("a", 1.0)

def logged_flip(fleet):
    from transmogrifai_tpu.obs.export import record_event
    fleet.set_fidelity_route("a", "a_int8")
    record_event("autopilot_actuation", action="fidelity")

def suppressed_flip(fleet):
    fleet.set_fidelity_route("a", None)  # autopilot-ok: operator CLI

def reader(router):
    return router.pressure("a")
'''


def test_lint_l022_flags_unlogged_actuations():
    findings = [f for f in L.lint_source(
        _L022_SRC, path="transmogrifai_tpu/serving/ops_tool.py")
        if f.code == "L022"]
    assert len(findings) == 3
    flagged = {f.line for f in findings}
    suppressed = [f for f in findings if f.suppression == "annotation"]
    assert len(suppressed) == 1 and not suppressed[0].gating
    assert all("flight-recorder" in f.message for f in findings)
    # the logged and read-only functions are clean
    src_lines = _L022_SRC.splitlines()
    for ln in flagged:
        assert "set_" in src_lines[ln - 1] or "rebucket" in \
            src_lines[ln - 1]


def test_lint_l022_allowlists_controller_and_tests():
    for path in ("transmogrifai_tpu/serving/autopilot.py",
                 "transmogrifai_tpu/serving/chaos.py",
                 "transmogrifai_tpu/serving/router_smoke.py",
                 "tests/test_autopilot.py"):
        assert not any(f.code == "L022"
                       for f in L.lint_source(_L022_SRC, path=path))


def test_lint_l022_repo_clean():
    import os
    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "transmogrifai_tpu")
    findings = [f for f in L.lint_paths([pkg]) if f.code == "L022"
                and f.gating]
    assert findings == []
