"""obs/ — unified observability layer: hierarchical span tracing,
Perfetto export, goodput accounting, process-wide metrics registry.

The marquee test (`TestUnifiedTimeline`) is the PR's acceptance
criterion: ONE trace in which fault-injected ingest retries, a killed
and resumed sweep, retry-backoff spans, and recompile events all nest
under a single run root span — and the GoodputReport attributes each
injected badput to its bucket.
"""

import json
import threading
import time

import numpy as np
import pytest

from transmogrifai_tpu.obs import export as obsx
from transmogrifai_tpu.obs import goodput as obsg
from transmogrifai_tpu.obs.metrics import (
    Histogram, MetricsRegistry, get_registry)
from transmogrifai_tpu.obs.trace import TRACER, Span, Tracer, add_event


# --------------------------------------------------------------------- #
# span tracer                                                           #
# --------------------------------------------------------------------- #

class TestSpans:
    def test_nesting_via_contextvar(self):
        tr = Tracer()
        with tr.span("outer") as outer:
            with tr.span("inner") as inner:
                assert tr.current() is inner
            assert tr.current() is outer
        assert tr.current() is None
        assert inner.parent_id == outer.span_id
        assert inner.trace_id == outer.trace_id

    def test_new_trace_roots_fresh_correlation_id(self):
        tr = Tracer()
        with tr.span("a") as a:
            with tr.span("b", new_trace=True) as b:
                pass
        assert b.parent_id is None
        assert b.trace_id != a.trace_id

    def test_new_trace_accepts_explicit_id(self):
        # the runner passes its run_id so trace/profile/event-log share it
        tr = Tracer()
        with tr.span("r", new_trace=True, trace_id="run-7") as r:
            with tr.span("c") as c:
                pass
        assert r.trace_id == "run-7"
        assert c.trace_id == "run-7"

    def test_attributes_and_events(self):
        tr = Tracer()
        with tr.span("s", category="test", k=1) as sp:
            sp.set(rows=10)
            sp.event("marker", x=2)
        j = sp.to_json()
        assert j["attributes"] == {"k": 1, "rows": 10}
        assert j["events"][0]["name"] == "marker"
        assert j["events"][0]["x"] == 2
        assert 0 <= j["events"][0]["offset_s"]

    def test_error_recorded_and_reraised(self):
        tr = Tracer()
        with pytest.raises(ValueError):
            with tr.span("boom") as sp:
                raise ValueError("bad")
        assert sp.error == "ValueError: bad"
        assert sp.end_s is not None
        assert tr.current() is None  # context restored despite the raise

    def test_base_exception_recorded(self):
        from transmogrifai_tpu.runtime.faults import InjectedKill
        tr = Tracer()
        with pytest.raises(InjectedKill):
            with tr.span("killed") as sp:
                raise InjectedKill("site", 1)
        assert "InjectedKill" in sp.error

    def test_explicit_parent_across_threads(self):
        tr = Tracer()
        got = {}

        def worker(parent):
            # a fresh thread has NO inherited context...
            got["inherited"] = tr.current()
            with tr.span("child", parent=parent) as c:
                got["child"] = c

        with tr.span("root") as root:
            t = threading.Thread(target=worker, args=(root,))
            t.start()
            t.join()
        assert got["inherited"] is None
        assert got["child"].parent_id == root.span_id
        assert got["child"].trace_id == root.trace_id
        assert got["child"].thread_id != root.thread_id

    def test_trace_spans_filters_and_sorts(self):
        tr = Tracer()
        with tr.span("r", new_trace=True) as r:
            with tr.span("c1"):
                pass
            with tr.span("c2"):
                pass
        with tr.span("other", new_trace=True):
            pass
        spans = tr.trace_spans(r.trace_id)
        assert [s.name for s in spans] == ["r", "c1", "c2"]

    def test_live_spans_included(self):
        tr = Tracer()
        with tr.span("open", new_trace=True) as r:
            spans = tr.trace_spans(r.trace_id)
            assert [s.name for s in spans] == ["open"]
            assert spans[0].duration_s >= 0.0  # live: end = now

    def test_bounded_ring_counts_drops(self):
        tr = Tracer(max_spans=4)
        for i in range(10):
            with tr.span(f"s{i}"):
                pass
        assert len(tr.spans()) == 4
        assert tr.dropped == 6

    def test_add_event_noop_without_span(self):
        assert add_event("orphan") in (False, True)  # must not raise

    def test_duration_uses_monotonic_clock(self, monkeypatch):
        # a wall-clock step mid-span must not corrupt the duration
        monkeypatch.setattr(time, "time", lambda: 4e9)
        tr = Tracer()
        with tr.span("steady") as sp:
            pass
        assert sp.duration_s < 1.0


# --------------------------------------------------------------------- #
# chrome-trace / Perfetto export + event log                            #
# --------------------------------------------------------------------- #

class TestExport:
    def _tree(self):
        tr = Tracer()
        with tr.span("root", new_trace=True, run_id="abc") as root:
            with tr.span("child", category="phase") as c:
                c.event("recompile", trace_s=0.01)
        return root, tr.trace_spans(root.trace_id)

    def test_chrome_trace_shape(self):
        root, spans = self._tree()
        obj = obsx.chrome_trace(spans)
        assert obj["traceEvents"]
        xs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == {"root", "child"}
        child = next(e for e in xs if e["name"] == "child")
        assert child["args"]["parent_id"] == root.span_id
        assert child["cat"] == "phase"
        instants = [e for e in obj["traceEvents"] if e["ph"] == "i"]
        assert instants and instants[0]["name"] == "recompile"
        metas = [e for e in obj["traceEvents"] if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in metas)
        assert any(e["name"] == "thread_name" for e in metas)

    def test_validate_accepts_good_trace(self):
        _, spans = self._tree()
        assert obsx.validate_chrome_trace(obsx.chrome_trace(spans)) == []

    def test_validate_rejects_bad_traces(self):
        assert obsx.validate_chrome_trace({}) != []
        assert obsx.validate_chrome_trace({"traceEvents": []}) != []
        bad_ts = {"traceEvents": [
            {"ph": "X", "name": "x", "ts": -5, "dur": 1, "pid": 0,
             "tid": 0, "args": {"span_id": 1, "parent_id": None}}]}
        assert any("ts" in p for p in obsx.validate_chrome_trace(bad_ts))
        orphan = {"traceEvents": [
            {"ph": "X", "name": "x", "ts": 0, "dur": 10, "pid": 0,
             "tid": 0, "args": {"span_id": 1, "parent_id": 99}}]}
        assert any("parent" in p for p in obsx.validate_chrome_trace(orphan))
        outside = {"traceEvents": [
            {"ph": "X", "name": "p", "ts": 0, "dur": 10, "pid": 0,
             "tid": 0, "args": {"span_id": 1, "parent_id": None}},
            {"ph": "X", "name": "c", "ts": 50_000, "dur": 10, "pid": 0,
             "tid": 0, "args": {"span_id": 2, "parent_id": 1}}]}
        assert any("outside parent" in p
                   for p in obsx.validate_chrome_trace(outside))

    def test_write_and_reload(self, tmp_path):
        _, spans = self._tree()
        path = obsx.write_chrome_trace(str(tmp_path / "t.json"), spans)
        with open(path) as f:
            assert obsx.validate_chrome_trace(json.load(f)) == []

    def test_event_log_correlation_ids(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        log = obsx.EventLog(path, run_id="run-42")
        obsx.install_event_log(log)
        try:
            obsx.emit_event("retry", site="ingest", attempt=1)
            obsx.emit_event("fault", site="sweep.run_block")
        finally:
            obsx.uninstall_event_log(log)
            log.close()
        obsx.emit_event("dropped")  # uninstalled: must be a no-op
        recs = [json.loads(line) for line in open(path)]
        assert [r["kind"] for r in recs] == ["retry", "fault"]
        assert all(r["run_id"] == "run-42" for r in recs)
        assert all("ts" in r for r in recs)

    def test_uninstall_only_clears_own_log(self, tmp_path):
        outer = obsx.EventLog(str(tmp_path / "a.jsonl"), run_id="a")
        stale = obsx.EventLog(str(tmp_path / "b.jsonl"), run_id="b")
        obsx.install_event_log(outer)
        try:
            obsx.uninstall_event_log(stale)  # not installed: no effect
            assert obsx.active_event_log() is outer
        finally:
            obsx.uninstall_event_log(outer)
            outer.close()
            stale.close()


# --------------------------------------------------------------------- #
# goodput accounting                                                    #
# --------------------------------------------------------------------- #

class TestGoodput:
    def test_buckets_sum_to_wall(self):
        tr = Tracer()
        with tr.span("run", new_trace=True) as root:
            with tr.span("retry:x", category="retry"):
                time.sleep(0.02)
            with tr.span("ingest:m", category="ingest") as ing:
                ing.set(upload_wait_s=0.005)
            root.event("recompile", trace_s=0.003)
            time.sleep(0.01)
        report = obsg.build_report(root, tr.trace_spans(root.trace_id))
        assert report.wall_s > 0
        assert report.buckets["retry_backoff_s"] >= 0.015
        assert report.buckets["ingest_wait_s"] == pytest.approx(0.005)
        assert report.buckets["recompile_s"] == pytest.approx(0.003)
        assert sum(report.buckets.values()) == pytest.approx(
            report.wall_s, rel=1e-6)
        assert 0.0 <= report.goodput_frac < 1.0
        assert report.counts["retries"] == 1
        assert report.counts["recompiles"] == 1

    def test_savings_and_redo_events(self):
        tr = Tracer()
        with tr.span("run", new_trace=True) as root:
            root.event("journal_resume", blocks=3, saved_s=1.5)
            root.event("oom_redo", wasted_s=0.01)
            root.event("fault", site="s")
            time.sleep(0.03)  # badput must fit inside wall (no clamping)
        report = obsg.build_report(root, tr.trace_spans(root.trace_id))
        assert report.savings["resume_saved_s"] == pytest.approx(1.5)
        assert report.counts["resumed_blocks"] == 3
        assert report.buckets["oom_redo_s"] == pytest.approx(0.01)
        assert report.counts["faults_injected"] == 1

    def test_overlapped_badput_clamped_to_wall(self):
        # worker-thread backoffs can overlap: badput must not exceed wall
        tr = Tracer()
        with tr.span("run", new_trace=True) as root:
            spans = []
            threads = [threading.Thread(
                target=lambda: spans.append(None) or time.sleep(0.03))
                for _ in range(1)]
            with tr.span("retry:a", category="retry", parent=root):
                time.sleep(0.01)
        # synthesize a second overlapping retry span wider than wall
        fake = Span("retry:b", category="retry", parent=root)
        fake.end_s = fake.start_s + 10 * root.duration_s
        report = obsg.build_report(
            root, list(tr.trace_spans(root.trace_id)) + [fake])
        assert sum(report.buckets.values()) == pytest.approx(
            report.wall_s, rel=1e-6)
        assert report.buckets["productive_s"] >= 0.0

    def test_report_json_shape(self):
        tr = Tracer()
        with tr.span("run", new_trace=True) as root:
            pass
        j = obsg.build_report(root, []).to_json()
        assert set(j) == {"wall_s", "trace_id", "goodput_frac", "buckets",
                          "savings", "counts"}
        assert "productive_s" in j["buckets"]


# --------------------------------------------------------------------- #
# metrics registry: exposition + concurrency (satellite)                #
# --------------------------------------------------------------------- #

class TestMetricsExposition:
    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("esc_total", "h", path='a"b\\c\nd').inc()
        text = reg.to_prometheus()
        series = [ln for ln in text.splitlines()
                  if ln.startswith("esc_total{")]
        assert len(series) == 1  # the newline in the value did not split
        assert r'path="a\"b\\c\nd"' in series[0]

    def test_help_escaping(self):
        reg = MetricsRegistry()
        reg.counter("h_total", "line1\nline2 \\ backslash").inc()
        help_lines = [ln for ln in reg.to_prometheus().splitlines()
                      if ln.startswith("# HELP")]
        assert help_lines == [r"# HELP h_total line1\nline2 \\ backslash"]

    def test_histogram_bucket_ordering(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "h", bounds=(0.1, 0.5, 2.0))
        for v in (0.05, 0.3, 0.3, 1.0, 99.0):
            h.observe(v)
        pairs = h.bucket_counts()
        assert [b for b, _ in pairs] == [0.1, 0.5, 2.0, float("inf")]
        counts = [c for _, c in pairs]
        assert counts == sorted(counts), "cumulative counts must not dip"
        assert pairs[-1] == (float("inf"), 5)
        # text form: ascending le with +Inf last, _count matches
        lines = [ln for ln in reg.to_prometheus().splitlines()
                 if ln.startswith("lat_seconds_bucket")]
        assert [ln.rsplit(" ", 1)[1] for ln in lines] == \
            ["1", "3", "4", "5"]
        assert 'le="+Inf"' in lines[-1]
        assert "lat_seconds_count 5" in reg.to_prometheus()

    def test_concurrent_counter_and_histogram(self):
        reg = MetricsRegistry()
        c = reg.counter("conc_total")
        h = reg.histogram("conc_seconds", bounds=(0.5, 1.0))
        n_threads, per = 8, 500

        def work(i):
            for k in range(per):
                c.inc()
                h.observe((i + k) % 2)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * per
        assert h.count == n_threads * per
        assert h.bucket_counts()[-1][1] == n_threads * per

    def test_exemplar_exposition_and_escaping(self):
        reg = MetricsRegistry()
        h = reg.histogram("ex_seconds", "h", bounds=(0.1, 1.0),
                          phase="parse")
        h.observe(0.05, exemplar="trace-1")
        h.observe(0.5)                       # no exemplar on this bucket
        h.observe(5.0, exemplar='t"2\\x')    # +Inf bucket, hostile id
        text = reg.to_prometheus()
        b = [ln for ln in text.splitlines()
             if ln.startswith("ex_seconds_bucket")]
        assert len(b) == 3
        # OpenMetrics exemplar: ` # {trace_id="..."} value ts` on the
        # buckets that hold one; the escaping keeps the line parseable
        assert '# {trace_id="trace-1"} 0.05' in b[0]
        assert "# {" not in b[1]
        assert r'# {trace_id="t\"2\\x"}' in b[2]
        # last-write-wins per bucket
        h.observe(0.01, exemplar="trace-9")
        assert any(e[1] == "trace-9" for e in h.exemplars())
        assert not any(e[1] == "trace-1" for e in h.exemplars())

    def test_exemplar_json_parity(self):
        reg = MetricsRegistry()
        h = reg.histogram("par_seconds", "h", bounds=(0.1, 1.0),
                          phase="dispatch")
        h.observe(0.05, exemplar="tid-a")
        h.observe(0.5, exemplar="tid-b")
        entry = reg.to_json()["par_seconds"]["series"][0]
        assert entry["labels"] == {"phase": "dispatch"}
        got = {(e["le"], e["trace_id"]) for e in entry["exemplars"]}
        assert got == {(0.1, "tid-a"), (1.0, "tid-b")}
        # the ?format=json values mirror exactly what exemplars() holds
        assert {(b, i) for b, i, _, _ in h.exemplars()} == got

    def test_scrape_under_concurrent_writes_with_exemplars(self):
        # the satellite contract: scraping (text + json) while writer
        # threads hammer the labeled serving_phase_seconds family (with
        # exemplars) never crashes, never emits a malformed line, and
        # cumulative bucket counts never dip within one scrape
        reg = MetricsRegistry()
        phases = ("parse", "queue_wait", "pad", "dispatch")
        stop = threading.Event()
        errors = []

        def writer(i):
            k = 0
            while not stop.is_set():
                h = reg.histogram("serving_phase_seconds", "h",
                                  phase=phases[k % len(phases)])
                h.observe((k % 7) / 10.0,
                          exemplar=f"trace-{i}-{k}" if k % 3 == 0
                          else None)
                k += 1

        def scraper():
            while not stop.is_set():
                try:
                    text = reg.to_prometheus()
                    j = reg.to_json()
                except Exception as e:  # pragma: no cover - the assert
                    errors.append(repr(e))
                    return
                per_series = {}
                for ln in text.splitlines():
                    if ln.startswith("serving_phase_seconds_bucket"):
                        key = ln.split("phase=")[1].split('"')[1]
                        val = int(ln.split(" # ")[0].rsplit(" ", 1)[1])
                        per_series.setdefault(key, []).append(val)
                for key, vals in per_series.items():
                    if vals != sorted(vals):
                        errors.append(f"cumulative dip in {key}: {vals}")
                        return
                for fam in j.values():
                    for entry in fam["series"]:
                        for ex in entry.get("exemplars", ()):
                            if not ex["trace_id"].startswith("trace-"):
                                errors.append(f"garbled exemplar: {ex}")
                                return

        writers = [threading.Thread(target=writer, args=(i,))
                   for i in range(4)]
        scrapers = [threading.Thread(target=scraper) for _ in range(2)]
        for t in writers + scrapers:
            t.start()
        time.sleep(0.5)
        stop.set()
        for t in writers + scrapers:
            t.join(timeout=10)
        assert errors == []
        total = sum(
            e["count"] for e in
            reg.to_json()["serving_phase_seconds"]["series"])
        assert total > 0

    def test_concurrent_family_registration(self):
        reg = MetricsRegistry()
        out = []

        def grab():
            out.append(reg.counter("same_total", "h", shard="x"))

        threads = [threading.Thread(target=grab) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(o is out[0] for o in out), "one series, not eight"

    def test_serving_import_path_still_works_but_warns(self):
        # the compatibility contract: serving.metrics is obs.metrics —
        # and importing the shim says so with a DeprecationWarning
        import importlib

        import pytest

        from transmogrifai_tpu.obs import metrics as om
        with pytest.warns(DeprecationWarning,
                          match="obs.metrics instead"):
            import transmogrifai_tpu.serving.metrics as sm
            sm = importlib.reload(sm)  # warn even if already imported
        assert sm.MetricsRegistry is om.MetricsRegistry
        assert sm.Histogram is om.Histogram
        assert sm.REGISTRY is om.REGISTRY

    def test_serve_metrics_surface_includes_global_registry(self):
        # /metrics = service registry + process-global obs registry
        from transmogrifai_tpu.serving.http import metrics_json, metrics_text

        class _Stub:
            registry = MetricsRegistry()

        svc = _Stub()
        svc.registry.counter("serving_requests_total", "h").inc(3)
        get_registry().counter(
            "train_stages_fitted_total", "estimators fitted").inc(0)
        get_registry().counter(
            "ingest_chunks_total", "chunks").inc(0)
        text = metrics_text(svc)
        assert "serving_requests_total 3.0" in text
        assert "train_stages_fitted_total" in text
        assert "ingest_chunks_total" in text
        merged = metrics_json(svc)
        assert "serving_requests_total" in merged
        assert "train_stages_fitted_total" in merged


# --------------------------------------------------------------------- #
# MetricsRegistry.merge: the fleet-wide /metrics view (satellite)       #
# --------------------------------------------------------------------- #

class TestMetricsMerge:
    def test_counters_sum_into_same_labeled_series(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("req_total", "h", wire="json").inc(3)
        b.counter("req_total", "h", wire="json").inc(4)
        b.counter("req_total", "h", wire="binary").inc(1)
        merged = MetricsRegistry()
        merged.merge(a, replica="r1").merge(b, replica="r2")
        assert merged.find("req_total", wire="json").value == 7.0
        assert merged.find("req_total", wire="binary").value == 1.0
        # exposition: ONE fleet-total line per wire, no replica label
        lines = [ln for ln in merged.to_prometheus().splitlines()
                 if ln.startswith("req_total{")]
        assert sorted(lines) == ['req_total{wire="binary"} 1.0',
                                 'req_total{wire="json"} 7.0']

    def test_gauges_keep_per_replica_identity(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.gauge("queue_depth", "h", model="m1").set(2)
        b.gauge("queue_depth", "h", model="m1").set(9)
        merged = MetricsRegistry().merge(a, replica="r1").merge(
            b, replica="r2")
        # two replicas' depths must never collapse into one number
        assert merged.find("queue_depth", model="m1") is None
        text = merged.to_prometheus()
        assert 'queue_depth{model="m1",replica="r1"} 2' in text
        assert 'queue_depth{model="m1",replica="r2"} 9' in text

    def test_histograms_fold_buckets_when_ladders_agree(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        ha = a.histogram("lat_seconds", "h", bounds=(0.1, 1.0))
        hb = b.histogram("lat_seconds", "h", bounds=(0.1, 1.0))
        for v in (0.05, 0.5):
            ha.observe(v)
        hb.observe(5.0)
        merged = MetricsRegistry().merge(a, replica="r1").merge(
            b, replica="r2")
        h = merged.find("lat_seconds")
        assert h.count == 3
        assert h.sum == pytest.approx(5.55)
        assert h.bucket_counts() == [(0.1, 1), (1.0, 2),
                                     (float("inf"), 3)]
        assert "lat_seconds_count 3" in merged.to_prometheus()

    def test_histogram_ladder_mismatch_falls_back_to_labeled_series(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.histogram("lat_seconds", "h", bounds=(0.1, 1.0)).observe(0.05)
        b.histogram("lat_seconds", "h", bounds=(0.5,)).observe(0.05)
        merged = MetricsRegistry().merge(a, replica="r1").merge(
            b, replica="r2")
        # r1's ladder claimed the unlabeled series; r2's incompatible
        # ladder lands under its replica label instead of corrupting it
        assert merged.find("lat_seconds").count == 1
        assert merged.find("lat_seconds", replica="r2").count == 1

    def test_type_conflict_skipped_not_fatal(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("depth", "h").inc(1)
        b.gauge("depth", "h").set(9)
        merged = MetricsRegistry().merge(a, replica="r1")
        merged.merge(b, replica="r2")  # must not raise
        assert merged.find("depth").value == 1.0
        assert "depth 1.0" in merged.to_prometheus()

    def test_merge_is_a_snapshot_not_a_link(self):
        a = MetricsRegistry()
        c = a.counter("req_total")
        c.inc(2)
        merged = MetricsRegistry().merge(a, replica="r1")
        c.inc(10)  # later replica traffic must not mutate the scrape
        assert merged.find("req_total").value == 2.0

    def test_merge_returns_self_for_chaining(self):
        merged = MetricsRegistry()
        assert merged.merge(MetricsRegistry(), replica="x") is merged


# --------------------------------------------------------------------- #
# RunProfile satellites                                                 #
# --------------------------------------------------------------------- #

class TestRunProfile:
    def test_phase_records_on_error_and_reraises(self):
        from transmogrifai_tpu.utils.profiling import RunProfile
        prof = RunProfile(run_type="t")
        with pytest.raises(RuntimeError):
            with prof.phase("Training", n_rows=5):
                raise RuntimeError("fit exploded")
        assert len(prof.phases) == 1
        p = prof.phases[0]
        assert p.name == "Training"
        assert p.extra["n_rows"] == 5
        assert p.extra["error"] == "RuntimeError: fit exploded"
        assert p.duration_s >= 0.0

    def test_phase_records_on_injected_kill(self):
        from transmogrifai_tpu.runtime.faults import InjectedKill
        from transmogrifai_tpu.utils.profiling import RunProfile
        prof = RunProfile(run_type="t")
        with pytest.raises(InjectedKill):
            with prof.phase("Training"):
                raise InjectedKill("site", 1)
        assert prof.phases and "InjectedKill" in prof.phases[0].extra["error"]

    def test_durations_survive_wall_clock_steps(self, monkeypatch):
        from transmogrifai_tpu.utils import profiling
        prof = profiling.RunProfile(run_type="t")
        # simulate an NTP step: wall clock jumps forward mid-phase
        monkeypatch.setattr(profiling.time, "time",
                            lambda: 4_000_000_000.0)
        with prof.phase("Scoring"):
            pass
        assert prof.phases[0].duration_s < 1.0
        assert prof.app_duration_s < 60.0
        # started_at stays an epoch TIMESTAMP (set before the patch)
        assert prof.started_at < 4_000_000_000.0

    def test_phase_opens_obs_span(self):
        from transmogrifai_tpu.utils.profiling import RunProfile
        prof = RunProfile(run_type="t")
        with TRACER.span("root", new_trace=True) as root:
            with prof.phase("Scoring"):
                pass
        names = [s.name for s in TRACER.trace_spans(root.trace_id)]
        assert "phase:Scoring" in names

    def test_to_json_carries_run_id_and_goodput(self):
        from transmogrifai_tpu.utils.profiling import RunProfile
        prof = RunProfile(run_type="t", run_id="r-1")
        prof.goodput = {"wall_s": 1.0}
        j = prof.to_json()
        assert j["run_id"] == "r-1"
        assert j["goodput"] == {"wall_s": 1.0}


# --------------------------------------------------------------------- #
# lint L009 (satellite)                                                 #
# --------------------------------------------------------------------- #

class TestLintL009:
    def _lint(self, src):
        from transmogrifai_tpu.analysis.lint import lint_source
        return [f for f in lint_source(src) if f.code == "L009"]

    def test_flags_duration_subtraction(self):
        src = ("import time\n"
               "def f():\n"
               "    t0 = time.time()\n"
               "    return time.time() - t0\n")
        assert len(self._lint(src)) == 1

    def test_flags_aliased_module(self):
        src = ("import time as _time\n"
               "def f(t0):\n"
               "    return _time.time() - t0\n")
        assert len(self._lint(src)) == 1

    def test_flags_either_operand(self):
        src = ("import time\n"
               "def f(deadline):\n"
               "    return deadline - time.time()\n")
        assert len(self._lint(src)) == 1

    def test_timestamps_and_methods_are_fine(self):
        src = ("import time, datetime\n"
               "def f(x):\n"
               "    stamp = time.time()\n"
               "    t = datetime.datetime.now().time()\n"
               "    d = x - perf()\n"
               "    return stamp\n")
        assert self._lint(src) == []

    def test_repo_is_clean(self):
        import os
        from transmogrifai_tpu.analysis.lint import lint_paths
        pkg = os.path.join(os.path.dirname(__file__), "..",
                           "transmogrifai_tpu")
        assert [f for f in lint_paths([pkg]) if f.code == "L009"] == []


# --------------------------------------------------------------------- #
# the acceptance criterion: one unified timeline                        #
# --------------------------------------------------------------------- #

def _sweep_inputs(n=160, seed=3):
    import jax.numpy as jnp
    rng = np.random.default_rng(seed)
    X = jnp.asarray(rng.normal(size=(n, 5)).astype(np.float32))
    y = jnp.asarray((rng.normal(size=n) > 0).astype(np.float32))
    folds = [((np.arange(n) % 2 != f).astype(np.float32),
              (np.arange(n) % 2 == f).astype(np.float32))
             for f in range(2)]
    return X, y, folds


class TestUnifiedTimeline:
    @pytest.fixture(autouse=True)
    def _fresh_tracer(self):
        TRACER.reset()
        yield
        TRACER.reset()

    def test_faulted_train_run_yields_one_attributed_trace(self, tmp_path):
        """Fault-injected ingest retries + a killed-then-resumed sweep,
        all inside one root span: ingest worker spans, sweep-block
        spans, retry-backoff spans, and recompile events parent under
        the run root, and the GoodputReport lands each injected badput
        in its bucket."""
        from transmogrifai_tpu.data.pipeline import run_chunk_pipeline
        from transmogrifai_tpu.evaluators import (
            BinaryClassificationEvaluator)
        from transmogrifai_tpu.models import OpLogisticRegression
        from transmogrifai_tpu.parallel.sweep import run_sweep
        from transmogrifai_tpu.runtime.faults import (
            SITE_READ_CHUNK, SITE_RUN_BLOCK, FaultPlan, FaultSpec,
            InjectedKill)
        from transmogrifai_tpu.runtime.journal import SweepJournal
        from transmogrifai_tpu.runtime.retry import RetryPolicy
        from transmogrifai_tpu.stages.base import FitContext

        X, y, folds = _sweep_inputs()
        ev = BinaryClassificationEvaluator()
        ctx = FitContext(n_rows=int(X.shape[0]), seed=7)
        # two static groups (max_iter 8 vs 4): two journal blocks
        grids = [{"reg_param": 0.01, "max_iter": 8},
                 {"reg_param": 0.1, "max_iter": 8},
                 {"reg_param": 0.02, "max_iter": 4}]
        jpath = str(tmp_path / "sweep.journal")

        with TRACER.span("run:train", category="run",
                         new_trace=True) as root:
            # 1) ingest with a transient read fault -> retried chunk
            chunks = [np.full((8, 4), i, np.float32) for i in range(4)]
            plan = FaultPlan([FaultSpec(SITE_READ_CHUNK, at=2,
                                        kind="error", transient=True)])
            policy = RetryPolicy(max_attempts=3, base_delay_s=0.02,
                                 jitter=0.0, seed=1)
            with plan.active():
                stats = run_chunk_pipeline(
                    chunks, prepare=lambda c: c * 2.0,
                    upload=lambda c: None, workers=2, depth=1,
                    label="test", retry=policy)
            assert stats.retries == 1

            # 2) sweep killed at block 2 (block 1 journals), then resumed
            plan = FaultPlan([FaultSpec(SITE_RUN_BLOCK, at=2,
                                        kind="kill")])
            with pytest.raises(InjectedKill):
                with plan.active():
                    run_sweep(OpLogisticRegression(), grids, X, y,
                              folds, ev, ctx,
                              journal=SweepJournal(jpath))
            resumed = run_sweep(OpLogisticRegression(), grids, X, y,
                                folds, ev, ctx,
                                journal=SweepJournal(jpath))
            assert all(row is not None for row in resumed)

        spans = TRACER.trace_spans(root.trace_id)
        by_cat = {}
        for s in spans:
            by_cat.setdefault(s.category, []).append(s)

        # every span reaches the root through parent links
        by_id = {s.span_id: s for s in spans}

        def reaches_root(s):
            while s.parent_id is not None:
                s = by_id[s.parent_id]
            return s.span_id == root.span_id

        assert all(reaches_root(s) for s in spans)

        # ingest worker spans under the ingest span under the root (the
        # retried chunk keeps ONE span — the retry loop runs inside it)
        assert by_cat["ingest"], "no ingest span"
        workers = by_cat["ingest_chunk"]
        assert len(workers) == len(chunks)
        ing = by_cat["ingest"][0]
        assert all(w.parent_id == ing.span_id for w in workers)

        # retry-backoff span nests under the retried worker chunk
        retries = by_cat["retry"]
        assert len(retries) == 1
        assert by_id[retries[0].parent_id].category == "ingest_chunk"

        # sweep blocks (killed run + resumed run) under the root
        blocks = by_cat["sweep"]
        assert len(blocks) >= 2
        killed = [b for b in blocks if b.error
                  and "InjectedKill" in b.error]
        assert killed, "the killed block span must record the kill"

        # recompile events fired inside sweep blocks
        recompiles = [e for s in spans for e in s.events
                      if e[0] == "recompile"]
        assert recompiles, "sweep compiles must emit recompile events"

        # resume event credits the journal with the skipped block
        resumes = [e for s in spans + [root] for e in s.events
                   if e[0] == "journal_resume"]
        assert resumes
        assert resumes[0][2]["blocks"] >= 1
        assert resumes[0][2]["saved_s"] > 0.0

        # goodput: injected badput lands in the right buckets
        report = obsg.build_report(root, spans)
        assert report.buckets["retry_backoff_s"] > 0.0
        assert report.buckets["fault_redo_s"] > 0.0  # the failed attempt
        assert report.buckets["recompile_s"] > 0.0
        assert report.counts["retries"] == 1
        assert report.counts["resumed_blocks"] >= 1
        assert report.savings["resume_saved_s"] > 0.0
        assert report.counts["faults_injected"] >= 1
        assert sum(report.buckets.values()) == pytest.approx(
            report.wall_s, rel=1e-6)

        # and the whole thing round-trips through the Perfetto exporter
        obj = obsx.chrome_trace([root] + spans)
        assert obsx.validate_chrome_trace(obj) == []

    def test_oom_halving_attributed_to_oom_redo(self):
        from transmogrifai_tpu.evaluators import (
            BinaryClassificationEvaluator)
        from transmogrifai_tpu.models import OpLogisticRegression
        from transmogrifai_tpu.parallel.sweep import run_sweep
        from transmogrifai_tpu.runtime.faults import (
            SITE_RUN_BLOCK, FaultPlan, FaultSpec)
        from transmogrifai_tpu.stages.base import FitContext

        X, y, folds = _sweep_inputs(seed=5)
        ev = BinaryClassificationEvaluator()
        ctx = FitContext(n_rows=int(X.shape[0]), seed=7)
        grids = [{"reg_param": 0.01, "max_iter": 6},
                 {"reg_param": 0.1, "max_iter": 6}]
        plan = FaultPlan([FaultSpec(SITE_RUN_BLOCK, at=1, kind="oom")])
        with TRACER.span("run:train", category="run",
                         new_trace=True) as root:
            with plan.active():
                out = run_sweep(OpLogisticRegression(), grids, X, y,
                                folds, ev, ctx)
        assert all(row is not None for row in out)
        report = obsg.build_report(root, TRACER.trace_spans(root.trace_id))
        assert report.buckets["oom_redo_s"] > 0.0
        assert report.counts["oom_redos"] == 1

    def test_journal_duration_roundtrip(self, tmp_path):
        from transmogrifai_tpu.runtime.journal import SweepJournal
        path = str(tmp_path / "d.journal")
        j = SweepJournal(path, meta={"sig": "x"})
        j.append({"a": 1}, [0.5, 0.6], duration_s=1.25)
        j.append({"a": 2}, [0.7, 0.8])  # no duration: older-writer shape
        j2 = SweepJournal(path, meta={"sig": "x"})
        assert j2.duration_of({"a": 1}) == pytest.approx(1.25)
        assert j2.duration_of({"a": 2}) == 0.0
        assert j2.lookup({"a": 1}) == [0.5, 0.6]


# --------------------------------------------------------------------- #
# fleet_mesh_rollup: pod-wide mesh utilization (satellite)              #
# --------------------------------------------------------------------- #

class TestFleetMeshRollup:
    def test_weighted_by_worker_wall(self):
        """Two hosts with different worker-wall must merge by raw
        busy/wall accumulators, not by averaging the per-host fracs."""
        a = {"workers": 2, "worker_wall_s": 10.0, "busy_s": 9.0,
             "utilization_frac": 0.9, "blocks": 3, "schedules": 1,
             "steals": 1, "requeues": 0, "idle_s": 1.0}
        b = {"workers": 2, "worker_wall_s": 30.0, "busy_s": 15.0,
             "utilization_frac": 0.5, "blocks": 5, "schedules": 1,
             "steals": 0, "requeues": 2, "idle_s": 15.0}
        out = obsg.fleet_mesh_rollup([a, b])
        assert out["hosts"] == 2 and out["workers"] == 4
        # 24/40, NOT mean(0.9, 0.5) = 0.7
        assert out["mesh_utilization_frac"] == pytest.approx(0.6)
        assert out["blocks"] == 8 and out["steals"] == 1
        assert out["requeues"] == 2 and out["schedules"] == 2

    def test_legacy_mesh_without_accumulators(self):
        """A mesh section predating worker_wall_s/busy_s contributes
        its utilization_frac at unit weight instead of vanishing."""
        legacy = {"workers": 4, "utilization_frac": 0.8}
        out = obsg.fleet_mesh_rollup([legacy, {}])
        assert out["hosts"] == 1  # the empty dict is skipped
        assert out["mesh_utilization_frac"] == pytest.approx(0.8)

    def test_empty_rollup(self):
        out = obsg.fleet_mesh_rollup([])
        assert out["hosts"] == 0
        assert out["mesh_utilization_frac"] == 0.0
