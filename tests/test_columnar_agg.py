"""Vectorized event-time aggregation vs the per-record Python fold
(the semantic oracle): exact parity + the 1M-event speed bar
(VERDICT r2 #7, `DataReader.scala:216-330`)."""

import time

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.aggregators import CutOffTime
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.readers.readers import (
    AggregateDataReader, ConditionalDataReader)


def _features():
    preds = [
        FeatureBuilder.Real("amount").from_column("amount").as_predictor(),
        FeatureBuilder.Integral("clicks").from_column("clicks").as_predictor(),
        FeatureBuilder.Binary("active").from_column("active").as_predictor(),
        FeatureBuilder.Date("last_seen").from_column("last_seen")
        .as_predictor(),
        FeatureBuilder.Percent("rate").from_column("rate").as_predictor(),
    ]
    resp = FeatureBuilder.RealNN("label").from_column("label").as_response()
    return preds + [resp]


def _events(n=3000, n_keys=300, seed=0, with_condition=False):
    rng = np.random.default_rng(seed)
    keys = rng.integers(n_keys, size=n)
    times = rng.integers(0, 1_000_000, size=n)
    rows = []
    for i in range(n):
        rows.append({
            "key": f"k{keys[i]}",
            "t": int(times[i]),
            "amount": (float(rng.normal()) if rng.uniform() > 0.1 else None),
            "clicks": int(rng.integers(0, 5)),
            "active": bool(rng.uniform() > 0.5),
            "last_seen": int(times[i]),
            "rate": float(rng.uniform()),
            "label": float(rng.uniform() > 0.5),
            "hit": bool(rng.uniform() > 0.7) if with_condition else False,
        })
    schema = {"key": t.ID, "t": t.Integral, "amount": t.Real,
              "clicks": t.Integral, "active": t.Binary,
              "last_seen": t.Date, "rate": t.Percent, "label": t.RealNN,
              "hit": t.Binary}
    ds = Dataset.from_rows(rows, schema=schema)
    return rows, ds


def _compare(ds_row: Dataset, ds_col: Dataset, features):
    # row order: both keyed datasets sorted by key for comparison
    def by_key(ds):
        keys = list(ds.column("key"))
        order = np.argsort(keys)
        return {name: np.asarray(ds.column(name), dtype=object)[order]
                for name in ds.names()}
    a, b = by_key(ds_row), by_key(ds_col)
    assert list(a["key"]) == list(b["key"])
    for f in features:
        for va, vb in zip(a[f.name], b[f.name]):
            if va is None or (isinstance(va, float) and np.isnan(va)):
                assert vb is None or (isinstance(vb, float) and np.isnan(vb)), \
                    (f.name, va, vb)
            elif isinstance(va, float):
                assert vb == pytest.approx(va, rel=1e-9), (f.name, va, vb)
            else:
                assert va == vb, (f.name, va, vb)


@pytest.mark.parametrize("cutoff", [
    None, CutOffTime.unix_epoch(500_000), CutOffTime.infinite_future()])
def test_aggregate_columnar_parity(cutoff):
    rows, ds = _events()
    features = _features()
    row_reader = AggregateDataReader(
        rows, key_fn=lambda r: r["key"], time_fn=lambda r: r["t"],
        cutoff=cutoff)
    col_reader = AggregateDataReader(
        ds, cutoff=cutoff, key_column="key", time_column="t")
    _compare(row_reader.read(features), col_reader.read(features), features)


@pytest.mark.parametrize("keep,drop", [
    ("min", False), ("max", True), ("random", False), ("random", True)])
def test_conditional_columnar_parity(keep, drop):
    rows, ds = _events(with_condition=True)
    features = _features()
    row_reader = ConditionalDataReader(
        rows, key_fn=lambda r: r["key"], time_fn=lambda r: r["t"],
        target_condition=lambda r: r["hit"], time_stamp_to_keep=keep,
        drop_if_not_met=drop, seed=7)
    col_reader = ConditionalDataReader(
        ds, time_stamp_to_keep=keep, drop_if_not_met=drop, seed=7,
        key_column="key", time_column="t", condition_column="hit")
    _compare(row_reader.read(features), col_reader.read(features), features)


def test_columnar_aggregate_1m_events_fast():
    """The scale bar: 1M events aggregate in seconds (the row path takes
    minutes at this size)."""
    n, n_keys = 1_000_000, 50_000
    rng = np.random.default_rng(1)
    ds = Dataset(
        {"key": np.char.add("k", rng.integers(
            n_keys, size=n).astype(str)).astype(object),
         "t": rng.integers(0, 1_000_000, size=n).astype(np.float64),
         "amount": rng.normal(size=n),
         "label": (rng.uniform(size=n) > 0.5).astype(np.float64)},
        {"key": t.ID, "t": t.Integral, "amount": t.Real, "label": t.RealNN})
    features = [
        FeatureBuilder.Real("amount").from_column("amount").as_predictor(),
        FeatureBuilder.RealNN("label").from_column("label").as_response()]
    reader = AggregateDataReader(
        ds, cutoff=CutOffTime.unix_epoch(500_000),
        key_column="key", time_column="t")
    t0 = time.time()
    out = reader.read(features)
    dt = time.time() - t0
    assert len(out) == n_keys
    assert dt < 30.0, f"1M-event columnar aggregate took {dt:.1f}s"
    # spot-check one key against the oracle fold
    kcol = np.asarray(out.column("key"))
    target = kcol[0]
    keys_all = np.asarray(ds.column("key")).astype(str)
    sel = keys_all == target
    ts = np.asarray(ds.column("t"))[sel]
    am = np.asarray(ds.column("amount"))[sel]
    expected = am[ts < 500_000].sum()
    got = out.column("amount")[0]
    assert got == pytest.approx(expected, rel=1e-9)
