"""serving/ subsystem: shape-bucketed micro-batching, AOT bucket warmup
(zero recompiles under mixed-size traffic), bounded-queue load shedding,
per-request deadlines, error quarantine, versioned hot-swap + rollback,
and the metrics surface (JSON + Prometheus) — plus the satellite paths:
ragged-tail padding in `score_stream` and the runner's per-batch latency
histogram."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.automl import transmogrify
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.serving import (
    MetricsRegistry, MicroBatcher, Request, ScoreError, ScoringService,
    ServingConfig, bucket_for, bucket_ladder)
from transmogrifai_tpu.obs.metrics import Histogram
from transmogrifai_tpu.workflow import Workflow


def _make_ds(n=160, seed=0):
    rng = np.random.default_rng(seed)
    age = rng.uniform(1, 80, n)
    fare = rng.lognormal(2.5, 1.0, n)
    sex = rng.choice(["male", "female"], n)
    logit = (sex == "female") * 2.0 + (age < 12) * 1.0 + \
        0.15 * np.log(fare) - 1.0
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    return Dataset(
        {"age": age, "fare": fare, "sex": sex.astype(object), "survived": y},
        {"age": t.Real, "fare": t.Real, "sex": t.PickList,
         "survived": t.Integral})


def _train(ds, reg_param=0.01, max_iter=60):
    preds, label = FeatureBuilder.from_dataset(ds, response="survived")
    vec = transmogrify(preds)
    pred = OpLogisticRegression(reg_param=reg_param, max_iter=max_iter) \
        .set_input(label, vec).get_output()
    return Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train()


ROWS = [{"age": 30.0, "fare": 12.0, "sex": "male"},
        {"age": 8.0, "fare": 30.0, "sex": "female"},
        {"age": 55.0, "fare": 80.0, "sex": "female"},
        {"age": 41.0, "fare": 7.0, "sex": "male"}]


@pytest.fixture(scope="module")
def model_dirs(tmp_path_factory):
    """Two saved model versions (different fits → different fingerprints)
    + the training dataset."""
    base = tmp_path_factory.mktemp("serving-models")
    ds = _make_ds()
    m1 = _train(ds, reg_param=0.01)
    m1.save(str(base / "v1"))
    m2 = _train(ds, reg_param=0.5, max_iter=30)
    m2.save(str(base / "v2"))
    return ds, str(base / "v1"), str(base / "v2")


@pytest.fixture(scope="module")
def svc(model_dirs):
    """Shared read-only service over v1 (warm, max_batch=8)."""
    _, v1, _ = model_dirs
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=8, batch_wait_ms=1.0))
    service.start()
    yield service
    service.stop()


# --------------------------------------------------------------------- #
# bucket ladder + batcher units                                         #
# --------------------------------------------------------------------- #

def test_bucket_ladder_and_lookup():
    assert bucket_ladder(64) == (1, 2, 4, 8, 16, 32, 64)
    assert bucket_ladder(48) == (1, 2, 4, 8, 16, 32, 48)  # cap reachable
    ladder = bucket_ladder(16, min_bucket=4)
    assert ladder == (4, 8, 16)
    assert bucket_for(1, ladder) == 4
    assert bucket_for(9, ladder) == 16
    with pytest.raises(ScoreError) as ei:
        bucket_for(17, ladder)
    assert ei.value.code == "bad_request"


def test_microbatcher_coalesces_queued_requests():
    """Everything queued when the scoring thread arrives lands in ONE
    batch (up to max_batch rows)."""
    mb = MicroBatcher(max_queue=16, max_batch=8, batch_wait_s=0.0)
    ds = _make_ds(2)
    reqs = [Request(ds, deadline=None) for _ in range(3)]
    for r in reqs:
        mb.put(r)
    batch, expired = mb.next_batch()
    assert batch == reqs and not expired
    assert sum(r.n_rows for r in batch) == 6


def test_microbatcher_bounded_queue_and_close():
    mb = MicroBatcher(max_queue=2, max_batch=8)
    ds = _make_ds(1)
    mb.put(Request(ds, None))
    mb.put(Request(ds, None))
    with pytest.raises(ScoreError) as ei:
        mb.put(Request(ds, None))
    assert ei.value.code == "queue_full"
    drained = mb.close()
    assert len(drained) == 2
    with pytest.raises(ScoreError) as ei:
        mb.put(Request(ds, None))
    assert ei.value.code == "shutdown"


def test_microbatcher_carries_oversized_head():
    """A request that does not fit the remaining budget waits for the
    NEXT batch — never dropped, never reordered past its peers."""
    mb = MicroBatcher(max_queue=16, max_batch=4, batch_wait_s=0.0)
    big = Request(_make_ds(3), None)
    big2 = Request(_make_ds(3), None)
    mb.put(big)
    mb.put(big2)
    batch1, _ = mb.next_batch()
    assert batch1 == [big]        # 3 + 3 > 4: second carries over
    batch2, _ = mb.next_batch()
    assert batch2 == [big2]


# --------------------------------------------------------------------- #
# metrics registry                                                      #
# --------------------------------------------------------------------- #

def test_histogram_quantiles():
    h = Histogram(bounds=(0.1, 1.0, 10.0))
    for v in (0.05,) * 50 + (5.0,) * 50:
        h.observe(v)
    assert h.count == 100
    p50 = h.quantile(0.5)
    assert p50 is not None and p50 <= 1.0
    p99 = h.quantile(0.99)
    assert 1.0 < p99 <= 10.0
    s = h.summary()
    assert s["count"] == 100 and s["max"] == 5.0 and s["p95"] > 1.0
    assert Histogram().quantile(0.5) is None  # empty


def test_registry_json_and_prometheus():
    r = MetricsRegistry()
    r.counter("reqs_total", "requests", route="score").inc(3)
    r.gauge("depth", "queue depth").set(2)
    r.histogram("lat_seconds", "latency").observe(0.02)
    j = r.to_json()
    assert j["reqs_total"]["series"][0] == {
        "labels": {"route": "score"}, "value": 3.0}
    assert j["lat_seconds"]["series"][0]["count"] == 1
    text = r.to_prometheus()
    assert '# TYPE reqs_total counter' in text
    assert 'reqs_total{route="score"} 3.0' in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert 'lat_seconds_count 1' in text
    assert text.endswith("\n")
    with pytest.raises(ValueError):
        r.gauge("reqs_total")  # type clash


# --------------------------------------------------------------------- #
# padded scoring parity                                                 #
# --------------------------------------------------------------------- #

def test_score_padded_matches_unpadded(model_dirs):
    from transmogrifai_tpu.workflow.serialization import load_model
    ds, v1, _ = model_dirs
    model = load_model(v1)
    scorer = model._ensure_compiled()
    small = ds.take(np.arange(5))
    plain = scorer(small)
    padded = scorer.score_padded(small, 16)
    for name, v in plain.items():
        pv = padded[name]
        if isinstance(v, dict):
            for k in v:
                a, b = np.asarray(v[k]), np.asarray(pv[k])
                assert a.shape == b.shape
                if a.dtype != object:
                    np.testing.assert_allclose(a, b, rtol=1e-5)
        else:
            assert np.asarray(pv).shape[0] == 5


def test_pad_dataset_validates():
    from transmogrifai_tpu.workflow.compiled import pad_dataset
    ds = _make_ds(4)
    assert pad_dataset(ds, 4) is ds
    padded = pad_dataset(ds, 7)
    assert len(padded) == 7
    # pad rows repeat the LAST real row
    assert padded.column("age")[6] == ds.column("age")[3]
    with pytest.raises(ValueError):
        pad_dataset(ds, 2)


# --------------------------------------------------------------------- #
# service: scoring, coalescing, warmup                                  #
# --------------------------------------------------------------------- #

def test_score_basic_and_row_shape(svc):
    res = svc.score(list(ROWS))
    assert res.n_rows == 4 and res.model_version
    rows = res.rows()
    assert len(rows) == 4
    pred = next(v for v in rows[0].values()
                if isinstance(v, dict) and "prediction" in v)
    assert pred["prediction"] in (0.0, 1.0)
    assert 0.0 <= pred["probability_1"] <= 1.0


def test_concurrent_requests_coalesce_into_one_device_batch(model_dirs):
    """N clients blocked behind a slow in-flight batch coalesce into ONE
    device dispatch when the scoring thread frees up."""
    _, v1, _ = model_dirs
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=8, batch_wait_ms=1.0))
    service.start()
    try:
        started, release = threading.Event(), threading.Event()
        version = service._active
        orig = version.scorer.score_padded

        def gate(ds, bucket):
            started.set()
            release.wait(10)
            return orig(ds, bucket)

        version.scorer.score_padded = gate
        results = {}

        def client(i):
            results[i] = service.score([ROWS[i % len(ROWS)]])

        t0 = threading.Thread(target=client, args=(99,))  # the gated warm
        t0.start()
        assert started.wait(10)   # scoring thread is now busy
        clients = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for th in clients:
            th.start()
        time.sleep(0.2)           # let all four enqueue behind the gate
        version.scorer.score_padded = orig
        release.set()
        t0.join(10)
        for th in clients:
            th.join(10)
        assert len(results) == 5
        versions = {r.model_version for r in results.values()}
        assert versions == {version.version_id}
        # exactly TWO device dispatches total: the gated warm request,
        # then the four waiting requests coalesced into ONE batch
        assert service._m_batches.value == 2
        reg = service.registry.to_json()
        per_bucket = {s["labels"]["bucket"]: s["value"] for s in
                      reg["serving_bucket_requests_total"]["series"]}
        assert per_bucket.get("4") == 4.0  # 4 rows → bucket 4
    finally:
        service.stop()


def test_no_recompiles_after_warmup_across_mixed_sizes(svc):
    """The acceptance property: AOT bucket warmup means mixed request
    sizes cause ZERO new jit traces (retrace counters are flat)."""
    from transmogrifai_tpu.analysis.retrace import MONITOR
    svc.score([ROWS[0]])  # ensure steady state
    before = MONITOR.snapshot()
    for size in (1, 3, 5, 8, 2, 7, 4, 6):
        rows = [ROWS[i % len(ROWS)] for i in range(size)]
        res = svc.score(rows)
        assert res.n_rows == size
    delta = MONITOR.delta(before)
    assert delta == {}, f"unexpected recompiles: {delta}"


def test_warmup_compile_counts_exported(svc):
    """Per-bucket compile counts from warmup land in the registry."""
    reg = svc.registry.to_json()
    series = reg["serving_bucket_compiles_total"]["series"]
    buckets = {s["labels"]["bucket"] for s in series}
    assert buckets == {"1", "2", "4", "8"}
    assert all(s["value"] >= 1 for s in series)


def test_warm_rows_larger_than_smallest_bucket(model_dirs):
    """Caller-provided warm rows exceeding a bucket are truncated for
    that bucket, not a construction-time crash."""
    _, v1, _ = model_dirs
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=4),
        warm_rows=[dict(ROWS[i % len(ROWS)]) for i in range(3)])
    service.start()
    try:
        assert service.score([ROWS[0]]).n_rows == 1
        counts = service._active.compile_counts
        assert set(counts) == {1, 2, 4}
    finally:
        service.stop()


def test_oversized_request_rejected_at_admission(svc):
    with pytest.raises(ScoreError) as ei:
        svc.score([dict(ROWS[0]) for _ in range(9)])  # max_batch=8
    assert ei.value.code == "bad_request"
    with pytest.raises(ScoreError) as ei:
        svc.score([])
    assert ei.value.code == "bad_request"


# --------------------------------------------------------------------- #
# overload: deadlines + load shedding + quarantine                      #
# --------------------------------------------------------------------- #

def _gated_service(v1, **cfg):
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=8, batch_wait_ms=1.0, **cfg))
    service.start()
    started, release = threading.Event(), threading.Event()
    version = service._active
    orig = version.scorer.score_padded

    def gate(ds, bucket):
        started.set()
        release.wait(10)
        return orig(ds, bucket)

    version.scorer.score_padded = gate

    def ungate():
        version.scorer.score_padded = orig
        release.set()

    return service, started, ungate


def test_deadline_exceeded_is_structured_and_service_survives(model_dirs):
    _, v1, _ = model_dirs
    service, started, ungate = _gated_service(v1)
    try:
        errs = {}

        def client(key, **kw):
            try:
                errs[key] = service.score([ROWS[0]], **kw)
            except ScoreError as e:
                errs[key] = e

        a = threading.Thread(target=client, args=("a",))
        a.start()
        assert started.wait(10)
        b = threading.Thread(target=client, args=("b",),
                             kwargs={"deadline_ms": 30})
        b.start()
        time.sleep(0.15)  # b's deadline passes while queued
        ungate()
        a.join(10)
        b.join(10)
        assert isinstance(errs["b"], ScoreError)
        assert errs["b"].code == "deadline_exceeded"
        assert not isinstance(errs["a"], ScoreError)  # batchmate unharmed
        # the service keeps serving after the shed
        res = service.score([ROWS[1]])
        assert res.n_rows == 1
        reg = service.registry.to_json()
        sheds = {s["labels"]["reason"]: s["value"]
                 for s in reg["serving_shed_total"]["series"]}
        assert sheds.get("deadline_exceeded", 0) >= 1
    finally:
        service.stop()


def test_queue_full_sheds_with_structured_error(model_dirs):
    _, v1, _ = model_dirs
    service, started, ungate = _gated_service(v1, max_queue=1)
    try:
        ok = {}

        def client(key):
            ok[key] = service.score([ROWS[0]], deadline_ms=20_000)

        a = threading.Thread(target=client, args=("a",))
        a.start()
        assert started.wait(10)
        b = threading.Thread(target=client, args=("b",))
        b.start()
        deadline = time.monotonic() + 5
        while service._batcher.depth() < 1:  # b is queued
            assert time.monotonic() < deadline
            time.sleep(0.005)
        with pytest.raises(ScoreError) as ei:
            service.score([ROWS[1]])  # queue at capacity → shed NOW
        assert ei.value.code == "queue_full"
        # queue gauge observed non-zero while saturated
        depth = service.registry.gauge("serving_queue_depth").value
        assert depth >= 1
        ungate()
        a.join(10)
        b.join(10)
        assert ok["a"].n_rows == 1 and ok["b"].n_rows == 1  # no drops
        reg = service.registry.to_json()
        sheds = {s["labels"]["reason"]: s["value"]
                 for s in reg["serving_shed_total"]["series"]}
        assert sheds.get("queue_full", 0) >= 1
    finally:
        service.stop()


def test_error_quarantine_fails_one_request_not_the_batch(model_dirs):
    """A poisoned batch re-scores request-by-request: the bad request
    gets a structured record_error, its batchmates get answers."""
    _, v1, _ = model_dirs
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=8, batch_wait_ms=1.0))
    service.start()
    try:
        started, release = threading.Event(), threading.Event()
        version = service._active
        orig = version.scorer.score_padded

        def poisoned(ds, bucket):
            if not started.is_set():
                started.set()
                release.wait(10)
                return orig(ds, bucket)
            if np.any(np.asarray(ds.column("age")) == -999.0):
                raise ValueError("poison record")
            return orig(ds, bucket)

        version.scorer.score_padded = poisoned
        results = {}

        def client(key, row):
            try:
                results[key] = service.score([row], deadline_ms=20_000)
            except ScoreError as e:
                results[key] = e

        warm = threading.Thread(target=client, args=("warm", ROWS[0]))
        warm.start()
        assert started.wait(10)
        bad_row = {"age": -999.0, "fare": 1.0, "sex": "male"}
        bad = threading.Thread(target=client, args=("bad", bad_row))
        good = threading.Thread(target=client, args=("good", ROWS[1]))
        bad.start()
        good.start()
        time.sleep(0.2)  # both coalesce behind the gate
        release.set()
        for th in (warm, bad, good):
            th.join(10)
        version.scorer.score_padded = orig
        assert isinstance(results["bad"], ScoreError)
        assert results["bad"].code == "record_error"
        assert results["good"].n_rows == 1  # batchmate survived
        assert results["warm"].n_rows == 1
        assert service.registry.counter("serving_errors_total").value == 1
        res = service.score([ROWS[2]])  # still serving
        assert res.n_rows == 1
    finally:
        service.stop()


# --------------------------------------------------------------------- #
# hot swap + rollback                                                   #
# --------------------------------------------------------------------- #

def test_hot_swap_under_load_never_misversions(model_dirs):
    """Traffic runs THROUGH the swap: every request succeeds, versions
    are only ever v1-then-v2 (monotonic per client), and rollback
    restores v1 instantly."""
    from transmogrifai_tpu.workflow.serialization import model_fingerprint
    _, v1, v2 = model_dirs
    fp1, fp2 = model_fingerprint(v1), model_fingerprint(v2)
    assert fp1 != fp2
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=8, batch_wait_ms=0.5))
    service.start()
    try:
        assert service.health()["model_version"] == fp1
        stop_traffic = threading.Event()
        seen = {0: [], 1: []}
        failures = []

        def client(i):
            while not stop_traffic.is_set():
                try:
                    res = service.score([ROWS[i % len(ROWS)]],
                                        deadline_ms=20_000)
                    seen[i].append(res.model_version)
                except ScoreError as e:  # pragma: no cover - must not happen
                    failures.append(e)
                    return

        threads = [threading.Thread(target=client, args=(i,))
                   for i in (0, 1)]
        for th in threads:
            th.start()
        deadline = time.monotonic() + 10
        while min(len(seen[0]), len(seen[1])) < 3:  # mid-flight traffic
            assert time.monotonic() < deadline
            time.sleep(0.01)
        swap = service.reload(v2)
        assert swap == {"status": "swapped", "version": fp2,
                        "previous": fp1}
        deadline = time.monotonic() + 10
        while not any(v == fp2 for v in seen[0][-3:]):
            assert time.monotonic() < deadline
            time.sleep(0.01)
        stop_traffic.set()
        for th in threads:
            th.join(10)
        assert not failures, failures
        for i in (0, 1):
            assert seen[i], "client scored nothing"
            assert set(seen[i]) <= {fp1, fp2}
            # monotonic: once v2 appears, v1 never comes back
            if fp2 in seen[i]:
                first_v2 = seen[i].index(fp2)
                assert all(v == fp2 for v in seen[i][first_v2:])
        # rollback: instant (already warm), traffic sees v1 again
        rb = service.rollback()
        assert rb["version"] == fp1 and rb["previous"] == fp2
        assert service.score([ROWS[0]]).model_version == fp1
        assert service.registry.counter(
            "serving_model_swaps_total").value == 2.0
    finally:
        service.stop()


def test_reload_same_dir_is_noop_and_rollback_without_history_errors(
        model_dirs):
    _, v1, _ = model_dirs
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=4))
    service.start()
    try:
        first = service.health()["model_version"]
        assert service.reload(v1) == {"status": "unchanged",
                                      "version": first}
        with pytest.raises(ScoreError) as ei:
            service.rollback()
        assert ei.value.code == "bad_request"
    finally:
        service.stop()


# --------------------------------------------------------------------- #
# HTTP frontend                                                         #
# --------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def http_server(svc):
    from transmogrifai_tpu.serving.http import serve
    server, _ = serve(svc, port=0, block=False)
    yield f"http://127.0.0.1:{server.port}"
    server.shutdown()
    server.server_close()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_http_score_and_healthz(http_server):
    status, body = _post(f"{http_server}/score", {"rows": ROWS[:2]})
    assert status == 200
    assert len(body["scores"]) == 2 and body["model_version"]
    assert body["latency_ms"] > 0
    status, single = _post(f"{http_server}/score", {"row": ROWS[0]})
    assert status == 200 and len(single["scores"]) == 1
    with urllib.request.urlopen(f"{http_server}/healthz",
                                timeout=30) as resp:
        health = json.loads(resp.read())
    assert resp.status == 200 and health["status"] == "ok"
    assert health["buckets"] == [1, 2, 4, 8]
    assert health["model_version"]


def test_http_metrics_prometheus_and_json(http_server):
    with urllib.request.urlopen(f"{http_server}/metrics",
                                timeout=30) as resp:
        text = resp.read().decode()
        ctype = resp.headers["Content-Type"]
    assert "text/plain" in ctype
    assert "# TYPE serving_request_latency_seconds histogram" in text
    assert "serving_request_latency_seconds_count" in text
    assert "# TYPE serving_queue_depth gauge" in text
    with urllib.request.urlopen(f"{http_server}/metrics?format=json",
                                timeout=30) as resp:
        data = json.loads(resp.read())
    lat = data["serving_request_latency_seconds"]["series"][0]
    assert lat["count"] >= 1 and lat["p50"] > 0  # non-zero latency data
    assert "serving_queue_depth" in data


def test_http_error_mapping(http_server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{http_server}/score", {"rows": "nope"})
    assert ei.value.code == 400
    assert json.loads(ei.value.read())["error"] == "bad_request"
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{http_server}/score",
              {"rows": [ROWS[0]] * 9})  # exceeds top bucket
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{http_server}/reload", {})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{http_server}/nope", {})
    assert ei.value.code == 404


def test_http_reload_bad_location_keeps_serving(http_server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{http_server}/reload",
              {"model_location": "/does/not/exist"})
    assert ei.value.code == 400
    status, body = _post(f"{http_server}/score", {"rows": ROWS[:1]})
    assert status == 200 and body["model_version"]


# --------------------------------------------------------------------- #
# satellites: ragged-tail streaming, runner latency, serve run type     #
# --------------------------------------------------------------------- #

def test_score_stream_ragged_tail_no_churn(model_dirs):
    """The final partial micro-batch pads to the warm shape instead of
    tracing a fresh program — retrace counters stay flat — and the
    padded tail's scores match the unpadded reference."""
    from transmogrifai_tpu.analysis.retrace import MONITOR
    from transmogrifai_tpu.workflow.serialization import load_model
    ds, v1, _ = model_dirs
    model = load_model(v1)
    n = len(ds)
    full, tail = 64, 23
    parts = [ds.take(np.arange(0, full)), ds.take(np.arange(full, 2 * full)),
             ds.take(np.arange(2 * full, 2 * full + tail))]
    # warm the full-batch shape once
    list(model.score_stream(iter(parts[:1])))
    before = MONITOR.snapshot()
    outs = list(model.score_stream(iter(parts)))
    assert MONITOR.delta(before) == {}, "ragged tail caused a retrace"
    assert len(outs) == 3
    pred_name = next(k for k, v in outs[2].items()
                     if isinstance(v, dict) and "prediction" in v)
    tail_probs = np.asarray(outs[2][pred_name]["probability"])
    assert tail_probs.shape[0] == tail  # pad rows sliced off
    ref = model.score_compiled(parts[2])[pred_name]
    np.testing.assert_allclose(tail_probs, np.asarray(ref["probability"]),
                               rtol=1e-5)
    # control: with pad_tail=False a FRESH ragged shape (17 — nothing
    # above traced it) recompiles, proving the monitor catches churn
    before = MONITOR.snapshot()
    list(model.score_stream(
        iter([parts[0], ds.take(np.arange(17))]), pad_tail=False))
    assert MONITOR.delta(before), "expected a retrace without tail padding"


def test_runner_streaming_score_records_batch_latency(model_dirs,
                                                      tmp_path):
    from transmogrifai_tpu.readers import DataReaders
    from transmogrifai_tpu.workflow import OpParams, WorkflowRunner
    ds, v1, _ = model_dirs
    rows = ds.to_rows()
    reader = DataReaders.stream(records=rows, batch_size=64)
    runner = WorkflowRunner(Workflow(), score_reader=reader)
    params = OpParams.from_json({"model_location": v1})
    result = runner.run("streaming-score", params)
    assert result.metrics["n_rows"] == len(ds)
    lat = result.metrics["batch_latency"]
    assert lat["count"] == result.batches
    assert lat["p50"] is not None and lat["p50"] > 0
    assert lat["p99"] >= lat["p50"]
    # the histogram also rides in the run profile (RunProfile.histograms)
    assert result.profile["histograms"][
        "streaming_batch_latency_s"]["count"] == result.batches


def test_runner_serve_run_type_bounded(model_dirs):
    """`serve` as a WorkflowRunner run type: boots the HTTP frontend,
    serves real requests for serve_duration_s, and reports the metrics
    registry in the RunResult."""
    from transmogrifai_tpu.workflow import OpParams, WorkflowRunner
    from transmogrifai_tpu.workflow.params import ServingParams
    _, v1, _ = model_dirs
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    params = OpParams(model_location=v1,
                      serving=ServingParams(port=port, max_batch=4),
                      custom_params={"serve_duration_s": 3.0})
    runner = WorkflowRunner(Workflow())
    box = {}

    def run():
        box["result"] = runner.run("serve", params)

    th = threading.Thread(target=run)
    th.start()
    base = f"http://127.0.0.1:{port}"
    deadline = time.monotonic() + 10
    scored = None
    while time.monotonic() < deadline and scored is None:
        try:
            _, scored = _post(f"{base}/score", {"rows": ROWS[:2]})
        except (urllib.error.URLError, ConnectionError):
            time.sleep(0.05)
    assert scored is not None and len(scored["scores"]) == 2
    th.join(30)
    result = box["result"]
    assert result.run_type == "serve"
    assert result.metrics["port"] == port
    assert result.metrics["model_version"] == scored["model_version"]
    reqs = result.metrics["serving"]["serving_requests_total"]["series"]
    assert reqs[0]["value"] >= 1


def test_serving_params_json_round_trip():
    from transmogrifai_tpu.workflow import OpParams
    from transmogrifai_tpu.workflow.params import ServingParams
    d = {"model_location": "/m",
         "serving": {"port": 9999, "max_batch": 32, "buckets": [4, 16, 32],
                     "batch_wait_ms": 5.0}}
    p = OpParams.from_json(d)
    assert isinstance(p.serving, ServingParams)
    assert p.serving.port == 9999
    cfg = p.serving.to_config()
    assert cfg.ladder() == (4, 16, 32)
    j = p.to_json()
    assert j["serving"]["max_batch"] == 32
    assert OpParams.from_json(json.loads(json.dumps(j))).serving.port == 9999
    assert OpParams.from_json({"model_location": "/m"}).serving is None


def test_lint_flags_fixed_leading_batch_dim():
    """L006: device code must not bake a fixed leading batch dim
    (incompatible with bucket padding); dynamic/-1 dims are clean."""
    from transmogrifai_tpu.analysis.lint import lint_source
    bad = (
        "import jax.numpy as jnp\n"
        "class S:\n"
        "    jittable = True\n"
        "    def device_apply(self, enc, dev):\n"
        "        x = dev[0]\n"
        "        y = x.reshape(128, -1)\n"
        "        z = jnp.broadcast_to(x, (256, 4))\n"
        "        return jnp.reshape(y, (64, 2))\n")
    codes = [f.code for f in lint_source(bad, "bad.py")]
    assert codes.count("L006") == 3
    clean = (
        "import jax.numpy as jnp\n"
        "class S:\n"
        "    jittable = True\n"
        "    def device_apply(self, enc, dev):\n"
        "        x = dev[0]\n"
        "        y = x.reshape(x.shape[0], -1)\n"
        "        z = x.reshape(-1, 1)\n"
        "        w = jnp.broadcast_to(x, (x.shape[0], 4))\n"
        "        return y.reshape(1, -1)\n")
    assert [f.code for f in lint_source(clean, "clean.py")] == []
    host = (
        "class H:\n"
        "    jittable = False\n"
        "    def device_apply(self, enc, dev):\n"
        "        return dev[0].reshape(128, -1)\n")
    assert [f.code for f in lint_source(host, "host.py")] == []


def test_mismatched_column_requests_quarantine_not_thread_death(model_dirs):
    """Requests with different column sets can coalesce; the assembly
    failure (Dataset.concat mismatch) must degrade to per-request scoring
    — the full-schema request succeeds, the partial one gets a structured
    error, and the scoring thread SURVIVES."""
    _, v1, _ = model_dirs
    service, started, ungate = _gated_service(v1)
    try:
        results = {}

        def client(key, row):
            try:
                results[key] = service.score([row], deadline_ms=20_000)
            except ScoreError as e:
                results[key] = e

        warm = threading.Thread(target=client, args=("warm", ROWS[0]))
        warm.start()
        assert started.wait(10)
        partial_row = {"age": 30.0, "fare": 9.0}  # no "sex" column
        a = threading.Thread(target=client, args=("full", ROWS[1]))
        b = threading.Thread(target=client, args=("partial", partial_row))
        a.start()
        b.start()
        time.sleep(0.2)  # coalesce both behind the gate
        ungate()
        for th in (warm, a, b):
            th.join(10)
        assert results["full"].n_rows == 1          # batchmate answered
        assert isinstance(results["partial"], ScoreError)
        # thread alive: the service still scores
        assert service.score([ROWS[2]]).n_rows == 1
    finally:
        service.stop()


def test_service_restarts_after_stop(model_dirs):
    _, v1, _ = model_dirs
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=4))
    service.start()
    assert service.score([ROWS[0]]).n_rows == 1
    service.stop()
    with pytest.raises(ScoreError):
        service.score([ROWS[0]])
    service.start()  # restart reopens admissions
    try:
        assert service.score([ROWS[1]]).n_rows == 1
    finally:
        service.stop()


def test_bad_deadline_is_bad_request(svc):
    with pytest.raises(ScoreError) as ei:
        svc.score([ROWS[0]], deadline_ms="fast")
    assert ei.value.code == "bad_request"


def test_rollback_updates_version_gauge(model_dirs):
    _, v1, v2 = model_dirs
    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=2))
    service.start()
    try:
        gauge = service.registry.gauge("serving_model_versions")
        assert gauge.value == 1.0
        service.reload(v2)
        assert gauge.value == 2.0
        service.rollback()
        assert gauge.value == 1.0
    finally:
        service.stop()


def test_reload_of_garbage_model_auto_rolls_back_live(model_dirs, tmp_path):
    """Satellite: the ROLLBACK half of the gated reload. A reload whose
    model scores garbage on the live holdout triggers automatic
    rollback — version gauge restored, `serving_rollbacks_total`
    ticked, and in-flight requests on the resident version unaffected
    throughout."""
    from transmogrifai_tpu.continual import gated_swap, live_holdout_metric
    ds, v1, _ = model_dirs
    # a garbage candidate: same schema, labels PERMUTED, so it passes
    # every integrity check and still predicts noise
    rng = np.random.default_rng(5)
    bad_ds = Dataset(
        {**{k: ds.columns[k] for k in ("age", "fare", "sex")},
         "survived": rng.permutation(np.asarray(ds.columns["survived"]))},
        dict(ds.schema))
    bad_dir = str(tmp_path / "garbage")
    _train(bad_ds, reg_param=5.0, max_iter=5).save(bad_dir)

    service = ScoringService.from_path(
        v1, config=ServingConfig(max_batch=8, batch_wait_ms=1.0))
    service.start()
    stop_traffic = threading.Event()
    traffic_errors: list = []

    def traffic():
        while not stop_traffic.is_set():
            try:
                service.score([ROWS[0]])
            except Exception as e:  # noqa: BLE001 - any drop fails the test
                traffic_errors.append(f"{type(e).__name__}: {e}")
            time.sleep(0.005)

    th = threading.Thread(target=traffic, daemon=True)
    th.start()
    try:
        v1_id = service.health()["model_version"]
        hold = ds.take(np.arange(64))
        y = np.asarray(hold.columns["survived"], np.float64)
        rows = [{k: r[k] for k in ("age", "fare", "sex")}
                for r in hold.to_rows()]
        baseline = live_holdout_metric(service, rows, y,
                                       classification=True)
        gauge = service.registry.gauge("serving_model_versions")
        result = gated_swap(service, bad_dir, rows, y,
                            baseline=baseline, tolerance=0.02,
                            registry=service.registry)
        assert result["status"] == "rolled_back", result
        assert service.health()["model_version"] == v1_id
        assert gauge.value == 1.0
        rb = service.registry.counter("serving_rollbacks_total")
        assert rb.value == 1.0
        # the restored version still answers
        assert service.score([ROWS[0]]).model_version == v1_id
    finally:
        stop_traffic.set()
        th.join(timeout=5)
        service.stop()
    assert not traffic_errors, traffic_errors[:3]


def test_score_stream_midstream_small_batch_not_padded(model_dirs):
    """Only the FINAL ragged batch pads; a mid-stream smaller batch is a
    real workload shape and passes through untouched (no silent compute
    multiplication)."""
    from transmogrifai_tpu.workflow.serialization import load_model
    ds, v1, _ = model_dirs
    model = load_model(v1)
    sizes = [40, 12, 40, 7]  # 12 is mid-stream, 7 is the tail
    parts, off = [], 0
    for s in sizes:
        parts.append(ds.take(np.arange(off, off + s)))
        off += s
    outs = list(model.score_stream(iter(parts)))
    pred_name = next(k for k, v in outs[0].items()
                     if isinstance(v, dict) and "probability" in v)
    got = [np.asarray(o[pred_name]["probability"]).shape[0] for o in outs]
    assert got == sizes  # mid-stream 12 stayed 12; tail 7 sliced back


def test_model_fingerprint_stability(model_dirs, tmp_path):
    from transmogrifai_tpu.workflow.serialization import (
        load_model, model_fingerprint)
    _, v1, v2 = model_dirs
    assert model_fingerprint(v1) == model_fingerprint(v1)  # deterministic
    assert model_fingerprint(v1) != model_fingerprint(v2)
    # a loaded model remembers its provenance, and a loaded-then-saved
    # copy still scores identically to its source (round-trip integrity)
    model = load_model(v1)
    assert model.loaded_from == v1
    resaved = tmp_path / "resaved"
    model.save(str(resaved))
    ds = _make_ds(8, seed=3)
    a = load_model(v1).score_compiled(ds)
    b = load_model(str(resaved)).score_compiled(ds)
    for name in a:
        if isinstance(a[name], dict) and "probability" in a[name]:
            np.testing.assert_allclose(
                np.asarray(a[name]["probability"]),
                np.asarray(b[name]["probability"]), rtol=1e-6)
