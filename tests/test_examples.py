"""Real-data example parity (VERDICT r1 #4): Titanic / Iris / Boston.

The checked-in datasets are the reference's own helloworld CSVs; the
example scripts mirror OpTitanicSimple / OpIrisSimple / OpBostonSimple
feature-for-feature. Assertions compare against the reference's published
Titanic holdout metrics (`/root/reference/README.md:85-90`, AuPR 0.8225)
and sanity bands for the other two (the reference publishes no numbers
for them).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

# full-grid parity lives in the slow suite; the DEFAULT profile runs the
# cut-down smoke below so the published-metric table cannot silently rot
# between full runs (r4 VERDICT #7)
slow = pytest.mark.slow


class TestDefaultProfileParitySmoke:
    """Cut-down Titanic + Boston parity in the FAST suite: the real
    example pipelines (same features, same data) under a 2-config grid
    and a single train/validation split, asserted against LOOSE bands
    around BASELINE.md's published metrics. Runs in well under a minute
    on CPU."""

    def test_titanic_smoke(self):
        import op_titanic_simple
        from transmogrifai_tpu.models import (
            OpLogisticRegression, OpXGBoostClassifier)
        models = [
            (OpLogisticRegression(max_iter=40), [{"reg_param": 0.01}]),
            (OpXGBoostClassifier(n_estimators=20, max_depth=3),
             [{"eta": 0.3}]),
        ]
        _, summary = op_titanic_simple.run(models=models)
        holdout = summary.holdout_metrics
        # reference publishes AuPR 0.8225 / AuROC 0.8822 on the full
        # grid; the 2-config smoke must stay within loose bands
        assert holdout["AuPR"] >= 0.70, holdout
        assert holdout["AuROC"] >= 0.75, holdout
        assert holdout["Error"] <= 0.30, holdout

    def test_boston_smoke(self):
        import op_boston_simple
        from transmogrifai_tpu.models import (
            OpLinearRegression, OpXGBoostRegressor)
        models = [
            (OpLinearRegression(), [{"reg_param": 0.01}]),
            (OpXGBoostRegressor(n_estimators=20, max_depth=3),
             [{"eta": 0.3}]),
        ]
        _, summary = op_boston_simple.run(models=models)
        assert summary.problem_type == "regression"
        assert summary.holdout_metrics["RMSE"] <= 7.5, summary.holdout_metrics
        assert summary.holdout_metrics["R2"] >= 0.5, summary.holdout_metrics


@pytest.fixture(scope="module")
def titanic():
    import op_titanic_simple
    return op_titanic_simple.run()


@slow
def test_titanic_aupr_parity(titanic):
    _, summary = titanic
    holdout = summary.holdout_metrics
    # published reference holdout AuPR is 0.8225; "within a few points"
    assert holdout["AuPR"] >= 0.78, holdout
    assert holdout["AuROC"] >= 0.80, holdout
    assert holdout["Error"] <= 0.25, holdout


@slow
def test_titanic_sweep_covers_default_families(titanic):
    _, summary = titanic
    families = {r.model for r in summary.validation_results}
    assert {"OpLogisticRegression", "OpRandomForestClassifier",
            "OpXGBoostClassifier"} <= families


@slow
def test_titanic_insights(titanic):
    model, _ = titanic
    insights = model.model_insights()
    ranked = sorted(insights.features, key=lambda f: -f.importance)
    top = {f.name for f in ranked[:6]}
    # sex / fare-derived features dominate survival prediction on Titanic
    assert top & {"sex", "estimatedCostOfTickets", "familySize"}, top


@slow
def test_iris_multiclass():
    import op_iris_simple
    _, summary = op_iris_simple.run()
    assert summary.problem_type == "multiclass"
    assert summary.holdout_metrics["F1"] >= 0.80, summary.holdout_metrics


@slow
def test_boston_regression():
    import op_boston_simple
    _, summary = op_boston_simple.run()
    assert summary.problem_type == "regression"
    # hand-tuned models land around RMSE 3-5 on Boston holdouts
    assert summary.holdout_metrics["RMSE"] <= 6.0, summary.holdout_metrics
    assert summary.holdout_metrics["R2"] >= 0.6, summary.holdout_metrics
