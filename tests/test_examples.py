"""Real-data example parity (VERDICT r1 #4): Titanic / Iris / Boston.

The checked-in datasets are the reference's own helloworld CSVs; the
example scripts mirror OpTitanicSimple / OpIrisSimple / OpBostonSimple
feature-for-feature. Assertions compare against the reference's published
Titanic holdout metrics (`/root/reference/README.md:85-90`, AuPR 0.8225)
and sanity bands for the other two (the reference publishes no numbers
for them).
"""

import os
import sys

import pytest

pytestmark = pytest.mark.slow  # helloworld example parity (minutes-long trains)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))


@pytest.fixture(scope="module")
def titanic():
    import op_titanic_simple
    return op_titanic_simple.run()


def test_titanic_aupr_parity(titanic):
    _, summary = titanic
    holdout = summary.holdout_metrics
    # published reference holdout AuPR is 0.8225; "within a few points"
    assert holdout["AuPR"] >= 0.78, holdout
    assert holdout["AuROC"] >= 0.80, holdout
    assert holdout["Error"] <= 0.25, holdout


def test_titanic_sweep_covers_default_families(titanic):
    _, summary = titanic
    families = {r.model for r in summary.validation_results}
    assert {"OpLogisticRegression", "OpRandomForestClassifier",
            "OpXGBoostClassifier"} <= families


def test_titanic_insights(titanic):
    model, _ = titanic
    insights = model.model_insights()
    ranked = sorted(insights.features, key=lambda f: -f.importance)
    top = {f.name for f in ranked[:6]}
    # sex / fare-derived features dominate survival prediction on Titanic
    assert top & {"sex", "estimatedCostOfTickets", "familySize"}, top


def test_iris_multiclass():
    import op_iris_simple
    _, summary = op_iris_simple.run()
    assert summary.problem_type == "multiclass"
    assert summary.holdout_metrics["F1"] >= 0.80, summary.holdout_metrics


def test_boston_regression():
    import op_boston_simple
    _, summary = op_boston_simple.run()
    assert summary.problem_type == "regression"
    # hand-tuned models land around RMSE 3-5 on Boston holdouts
    assert summary.holdout_metrics["RMSE"] <= 6.0, summary.holdout_metrics
    assert summary.holdout_metrics["R2"] >= 0.6, summary.holdout_metrics
