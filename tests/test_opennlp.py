# -*- coding: utf-8 -*-
"""OpenNLP binary model loader + decoders (VERDICT r3 #4 NER upgrade).

The loader reads the PUBLIC Apache OpenNLP 1.5 model format; these tests
exercise it against the PACKAGED models (`transmogrifai_tpu/resources/
opennlp/`, discovered by default — r4 VERDICT #5: a standalone checkout
with no env configuration runs real maxent decoding), and always cover
the format parser with a synthetic model."""

import io
import os
import struct
import zipfile

import pytest

from transmogrifai_tpu.utils.opennlp import (
    MaxentModel, NameFinder, SentenceDetector, TokenizerME, load_model,
    model_dir, token_class)

_DIR = model_dir()

needs_models = pytest.mark.skipif(
    _DIR is None, reason="no OpenNLP model directory available")


def test_packaged_models_discovered_without_env(monkeypatch):
    """With TRANSMOGRIFAI_OPENNLP_DIR unset the packaged resources dir
    is found — standalone deployments never silently fall back to
    heuristics."""
    monkeypatch.delenv("TRANSMOGRIFAI_OPENNLP_DIR", raising=False)
    d = model_dir()
    assert d is not None and d.endswith(os.path.join("resources", "opennlp"))
    from transmogrifai_tpu.utils.opennlp import available_models
    mods = available_models(d)
    for key in ("en-sent", "en-token", "en-pos-perceptron",
                "es-ner-person", "es-ner-location"):
        assert key in mods, key


def _path(name):
    return os.path.join(_DIR, name)


# ------------------------------------------------------------------ #
# format parser (synthetic model, no external files)                 #
# ------------------------------------------------------------------ #

def _java_utf(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">H", len(b)) + b


def test_parse_synthetic_gis_model(tmp_path):
    buf = io.BytesIO()
    buf.write(_java_utf("GIS"))
    buf.write(struct.pack(">i", 1))
    buf.write(struct.pack(">d", 0.0))
    buf.write(struct.pack(">i", 2))
    buf.write(_java_utf("yes"))
    buf.write(_java_utf("no"))
    # two patterns: first covers both outcomes (1 pred), second only "no"
    buf.write(struct.pack(">i", 2))
    buf.write(_java_utf("1 0 1"))
    buf.write(_java_utf("1 1"))
    buf.write(struct.pack(">i", 2))
    buf.write(_java_utf("f=a"))
    buf.write(_java_utf("f=b"))
    buf.write(struct.pack(">d", 2.0))   # f=a → yes
    buf.write(struct.pack(">d", -1.0))  # f=a → no
    buf.write(struct.pack(">d", 3.0))   # f=b → no
    p = tmp_path / "toy.bin"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("manifest.properties", "Component-Name=Toy\n")
        z.writestr("toy.model", buf.getvalue())
    m = load_model(str(p))
    assert m.outcomes == ["yes", "no"]
    assert m.best(["f=a"]) == "yes"
    assert m.best(["f=b"]) == "no"
    assert m.best(["f=unknown"]) in ("yes", "no")  # uniform, no crash
    probs = m.eval(["f=a"])
    assert abs(sum(probs) - 1.0) < 1e-9 and probs[0] > 0.9


def test_token_class_shapes():
    assert token_class("hello") == "lc"
    assert token_class("Hello") == "ic"
    assert token_class("HELLO") == "ac"
    assert token_class("H") == "sc"
    assert token_class("Mr.") == "cp"
    assert token_class("42") == "2d"
    assert token_class("1984") == "4d"
    assert token_class("12345") == "num"
    assert token_class("3rd") == "an"
    assert token_class("12-34") == "dd"
    assert token_class("1/2") == "ds"
    assert token_class("3.14") == "dp"
    assert token_class("!!") == "other"


# ------------------------------------------------------------------ #
# real models (the ones the reference ships)                         #
# ------------------------------------------------------------------ #

@needs_models
def test_sentence_detector_abbreviation_safe():
    sd = SentenceDetector(load_model(_path("en-sent.bin")))
    sents = sd.split("Mr. Smith went to Washington. He arrived on Tuesday. "
                     "The U.S. economy grew.")
    assert sents == ["Mr. Smith went to Washington.",
                     "He arrived on Tuesday.",
                     "The U.S. economy grew."]


@needs_models
def test_tokenizer_splits_punctuation():
    tk = TokenizerME(load_model(_path("en-token.bin")))
    toks = tk.tokenize("He said it, then left. Dr. Smith's dog barked!")
    assert "," in toks and "." in toks and "!" in toks
    assert "Dr." in toks          # abbreviation period NOT split
    assert "'s" in toks           # possessive split
    assert "said" in toks and "left" in toks


@needs_models
def test_spanish_person_name_finder():
    nf = NameFinder(load_model(_path("es-ner-person.bin")))
    toks = "El presidente Felipe González viajó a Madrid".split()
    spans = nf.spans(toks)
    names = [" ".join(toks[a:b]) for a, b, e in spans if e == "person"]
    assert names == ["Felipe González"]


@needs_models
def test_ner_stage_uses_models(monkeypatch):
    import numpy as np
    from transmogrifai_tpu.data.columns import Column
    from transmogrifai_tpu.ops.enrich import NameEntityRecognizer
    import transmogrifai_tpu.types as T
    st = NameEntityRecognizer(language="es", model_dir=_DIR)
    col = Column(T.Text, np.array(
        ["El presidente Felipe González viajó a Madrid.", None], dtype=object))
    out = st.transform([col])
    assert out.data[0] is not None
    assert "felipe gonzález" in out.data[0].get("Person", frozenset())
    assert out.data[1] is None


def test_ner_stage_heuristic_fallback():
    import numpy as np
    from transmogrifai_tpu.data.columns import Column
    from transmogrifai_tpu.ops.enrich import NameEntityRecognizer
    import transmogrifai_tpu.types as T
    st = NameEntityRecognizer(model_dir="/nonexistent")
    col = Column(T.Text, np.array(["Maria Lopez visited town"], dtype=object))
    out = st.transform([col])
    assert out.data[0] == {"Person": frozenset({"maria lopez"})}


@needs_models
def test_pos_tagger_perceptron():
    """POSTaggerME over the shipped perceptron model + tag dictionary:
    beam search with per-word allowed-tag constraints."""
    from transmogrifai_tpu.utils.opennlp import POSTagger, load_tag_dictionary
    path = _path("en-pos-perceptron.bin")
    tagger = POSTagger(load_model(path), load_tag_dictionary(path))
    toks = "The quick brown fox ran over the lazy dog .".split()
    tags = tagger.tag(toks)
    assert len(tags) == len(toks)
    assert tags[0] == "DT" and tags[-1] == "."
    assert tags[1] == "JJ" and tags[3] == "NN"
    toks2 = "She quickly sold three beautiful houses .".split()
    tags2 = tagger.tag(toks2)
    # tagdict constrains She→{NN,PRP}, sold→{VBD,VBN}
    assert tags2[0] == "PRP" and tags2[2] in ("VBD", "VBN")
    assert tags2[3] == "CD" and tags2[5] == "NNS"
