"""Workflow-level CV: in-fold feature-engineering refit (VERDICT r1 #3).

The contract (FitStagesUtil.cutDAG / OpValidator.applyDAG): with
`with_workflow_cv()`, estimators feeding the ModelSelector are re-fit
inside each fold, so a target-dependent stage fit on fold-global data
cannot leak validation labels into the CV metric.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # workflow-level CV re-fits

import transmogrifai_tpu.types as t
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.selector.model_selector import ModelSelector
from transmogrifai_tpu.selector.validators import OpCrossValidation
from transmogrifai_tpu.workflow import Workflow


def _noise_dataset(n=240, seed=11):
    rng = np.random.default_rng(seed)
    return Dataset(
        {"x": rng.normal(size=n),
         "y": (rng.uniform(size=n) > 0.5).astype(np.float64)},
        {"x": t.Real, "y": t.Integral})


def _leaky_pipeline():
    """Pure-noise predictor + random label, but a SUPERVISED bucketizer
    (fit on the label) between them: fit fold-globally it memorizes
    validation labels; fit in-fold it carries no signal."""
    x = FeatureBuilder.Real("x").from_column("x").as_predictor()
    y = FeatureBuilder.RealNN("y").from_column("y").as_response()
    buckets = x.auto_bucketize(y, max_depth=6)
    sel = ModelSelector(
        models=[(OpLogisticRegression(max_iter=30),
                 [{"reg_param": 0.0001}])],
        validator=OpCrossValidation(n_folds=3, seed=7),
        splitter=None,
        evaluator=BinaryClassificationEvaluator(metric="AuROC"))
    pred = sel.set_input(y, buckets).get_output()
    return y, pred


def _cv_metric(model, pred):
    summary = model.fitted[pred.origin_stage.uid].summary
    return summary.validation_results[0].mean_metric


def test_leaky_stage_scores_honestly_under_workflow_cv():
    ds = _noise_dataset()
    y, pred = _leaky_pipeline()

    leaky_model = (Workflow().set_result_features(pred, y)
                   .set_input_dataset(ds).train())
    honest_model = (Workflow().set_result_features(pred, y)
                    .set_input_dataset(ds).with_workflow_cv().train())

    leaky = _cv_metric(leaky_model, pred)
    honest = _cv_metric(honest_model, pred)
    # fold-global supervised buckets memorize validation labels; in-fold
    # refit removes the signal entirely (noise feature, random label)
    assert leaky > 0.62, f"expected optimistic leaky metric, got {leaky}"
    assert honest < 0.58, f"expected honest ~0.5 metric, got {honest}"
    assert leaky - honest > 0.08


def test_workflow_cv_parity_when_nothing_leaks():
    """With only unsupervised feature engineering, workflow CV must select
    the same winner and produce comparable metrics to plain CV."""
    rng = np.random.default_rng(3)
    n = 300
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    yv = (x1 + 0.5 * x2 + rng.normal(0, 0.7, size=n) > 0).astype(np.float64)
    ds = Dataset({"x1": x1, "x2": x2, "y": yv},
                 {"x1": t.Real, "x2": t.Real, "y": t.Integral})

    from transmogrifai_tpu.automl import transmogrify

    def build():
        preds, label = FeatureBuilder.from_dataset(ds, response="y")
        vec = transmogrify(preds)
        sel = ModelSelector(
            models=[(OpLogisticRegression(max_iter=25),
                     [{"reg_param": 0.001}, {"reg_param": 0.1}])],
            validator=OpCrossValidation(n_folds=3, seed=5),
            splitter=None,
            evaluator=BinaryClassificationEvaluator(metric="AuROC"))
        return label, sel.set_input(label, vec).get_output()

    y1, p1 = build()
    plain = (Workflow().set_result_features(p1, y1)
             .set_input_dataset(ds).train())
    y2, p2 = build()
    wcv = (Workflow().set_result_features(p2, y2)
           .set_input_dataset(ds).with_workflow_cv().train())

    s_plain = plain.fitted[p1.origin_stage.uid].summary
    s_wcv = wcv.fitted[p2.origin_stage.uid].summary
    assert s_plain.best_grid == s_wcv.best_grid
    m_plain = {tuple(sorted(r.grid.items())): r.mean_metric
               for r in s_plain.validation_results}
    m_wcv = {tuple(sorted(r.grid.items())): r.mean_metric
             for r in s_wcv.validation_results}
    for k in m_plain:
        # unsupervised stats (means/variances) differ slightly per fold but
        # the metrics must agree closely
        assert abs(m_plain[k] - m_wcv[k]) < 0.02, (k, m_plain[k], m_wcv[k])
