"""SanityChecker / MinVarianceFilter tests (reference: SanityCheckerTest.scala)."""

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.automl.sanity_checker import (
    MinVarianceFilter, SanityChecker, cramers_v)
from transmogrifai_tpu.data import Column
from transmogrifai_tpu.data.metadata import VectorColumnMetadata, VectorMetadata
from transmogrifai_tpu.stages.base import FeatureGeneratorStage, FitContext


def _vec_col(X, names=None, groups=None, indicators=None):
    X = np.asarray(X, dtype=np.float32)
    cols = []
    for i in range(X.shape[1]):
        cols.append(VectorColumnMetadata(
            parent_name=(names[i] if names else f"f{i}"),
            parent_type="Real",
            grouping=(groups[i] if groups else None),
            indicator_value=(indicators[i] if indicators else None)))
    meta = VectorMetadata("v", tuple(cols)).with_indices()
    return Column.vector(X, meta)


def _label(y):
    y = np.asarray(y, dtype=np.float64)
    return Column(t.RealNN, {"value": y, "mask": np.ones(len(y), dtype=bool)})


def _fit(est, label, vec):
    lf = FeatureGeneratorStage(name="y", ftype=t.RealNN, is_response=True).get_output()
    vf = FeatureGeneratorStage(name="v", ftype=t.OPVector).get_output()
    est.set_input(lf, vf)
    return est.fit([label, vec], FitContext(len(label.data["value"])))


def test_drops_low_variance_and_leakage():
    rng = np.random.default_rng(0)
    n = 400
    y = (rng.uniform(size=n) > 0.5).astype(float)
    good = rng.normal(size=n)
    constant = np.full(n, 3.0)
    leak = y * 2 - 1 + rng.normal(0, 1e-3, n)  # corr ≈ 1 with label
    X = np.stack([good, constant, leak], axis=1)
    model = _fit(SanityChecker(), _label(y), _vec_col(X, names=["good", "const", "leak"]))
    assert model.indices == [0]
    s = model.summary
    assert s["kept"] == [0]
    reasons = {st["name"]: st["dropped"] for st in s["stats"]}
    assert any("variance" in r for r in reasons["const_1"])
    assert any("label corr" in r for r in reasons["leak_2"])
    # transform slices kept columns
    out = model.transform([_label(y), _vec_col(X)])
    assert np.asarray(out.data).shape == (n, 1)
    assert model.output_meta().size == 1


def test_cramers_v_leakage_drop():
    rng = np.random.default_rng(1)
    n = 600
    y = (rng.uniform(size=n) > 0.5).astype(float)
    # categorical group perfectly aligned with the label (one-hot of y)
    cat_a = (y == 1).astype(np.float32)
    cat_b = (y == 0).astype(np.float32)
    noise = rng.normal(size=n).astype(np.float32)
    X = np.stack([cat_a, cat_b, noise], axis=1)
    vec = _vec_col(
        X, names=["c", "c", "x"], groups=["c", "c", None],
        indicators=["a", "b", None])
    model = _fit(SanityChecker(), _label(y), vec)
    assert model.indices == [2]  # both group columns dropped via Cramér's V
    stats = model.summary["stats"]
    assert stats[0]["cramersV"] == pytest.approx(1.0, abs=0.01)


def test_keeps_everything_when_clean():
    rng = np.random.default_rng(2)
    n = 300
    y = (rng.uniform(size=n) > 0.5).astype(float)
    X = rng.normal(size=(n, 4)).astype(np.float32)
    model = _fit(SanityChecker(), _label(y), _vec_col(X))
    assert model.indices == [0, 1, 2, 3]


def test_never_drops_all():
    n = 100
    y = np.zeros(n)
    X = np.ones((n, 2), dtype=np.float32)  # all constant
    model = _fit(SanityChecker(), _label(y), _vec_col(X))
    assert model.indices == [0, 1]  # retained despite flags


def test_cramers_v_function():
    # perfect association → 1
    assert cramers_v(np.array([[50, 0], [0, 50]])) == pytest.approx(1.0)
    # independence → 0
    assert cramers_v(np.array([[25, 25], [25, 25]])) == pytest.approx(0.0)
    assert cramers_v(np.zeros((2, 2))) == 0.0


def test_min_variance_filter():
    rng = np.random.default_rng(3)
    n = 200
    X = np.stack([rng.normal(size=n), np.full(n, 7.0)], axis=1)
    vf = FeatureGeneratorStage(name="v", ftype=t.OPVector).get_output()
    est = MinVarianceFilter().set_input(vf)
    model = est.fit([_vec_col(X)], FitContext(n))
    assert model.indices == [0]
    out = model.transform([_vec_col(X)])
    assert np.asarray(out.data).shape == (n, 1)
