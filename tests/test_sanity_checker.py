"""SanityChecker / MinVarianceFilter tests (reference: SanityCheckerTest.scala)."""

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.automl.sanity_checker import (
    MinVarianceFilter, SanityChecker, cramers_v)
from transmogrifai_tpu.data import Column
from transmogrifai_tpu.data.metadata import VectorColumnMetadata, VectorMetadata
from transmogrifai_tpu.stages.base import FeatureGeneratorStage, FitContext


def _vec_col(X, names=None, groups=None, indicators=None):
    X = np.asarray(X, dtype=np.float32)
    cols = []
    for i in range(X.shape[1]):
        cols.append(VectorColumnMetadata(
            parent_name=(names[i] if names else f"f{i}"),
            parent_type="Real",
            grouping=(groups[i] if groups else None),
            indicator_value=(indicators[i] if indicators else None)))
    meta = VectorMetadata("v", tuple(cols)).with_indices()
    return Column.vector(X, meta)


def _label(y):
    y = np.asarray(y, dtype=np.float64)
    return Column(t.RealNN, {"value": y, "mask": np.ones(len(y), dtype=bool)})


def _fit(est, label, vec):
    lf = FeatureGeneratorStage(name="y", ftype=t.RealNN, is_response=True).get_output()
    vf = FeatureGeneratorStage(name="v", ftype=t.OPVector).get_output()
    est.set_input(lf, vf)
    return est.fit([label, vec], FitContext(len(label.data["value"])))


def test_drops_low_variance_and_leakage():
    rng = np.random.default_rng(0)
    n = 400
    y = (rng.uniform(size=n) > 0.5).astype(float)
    good = rng.normal(size=n)
    constant = np.full(n, 3.0)
    leak = y * 2 - 1 + rng.normal(0, 1e-3, n)  # corr ≈ 1 with label
    X = np.stack([good, constant, leak], axis=1)
    model = _fit(SanityChecker(), _label(y), _vec_col(X, names=["good", "const", "leak"]))
    assert model.indices == [0]
    s = model.summary
    assert s["kept"] == [0]
    reasons = {st["name"]: st["dropped"] for st in s["stats"]}
    assert any("variance" in r for r in reasons["const_1"])
    assert any("label corr" in r for r in reasons["leak_2"])
    # transform slices kept columns
    out = model.transform([_label(y), _vec_col(X)])
    assert np.asarray(out.data).shape == (n, 1)
    assert model.output_meta().size == 1


def test_cramers_v_leakage_drop():
    rng = np.random.default_rng(1)
    n = 600
    y = (rng.uniform(size=n) > 0.5).astype(float)
    # categorical group perfectly aligned with the label (one-hot of y)
    cat_a = (y == 1).astype(np.float32)
    cat_b = (y == 0).astype(np.float32)
    noise = rng.normal(size=n).astype(np.float32)
    X = np.stack([cat_a, cat_b, noise], axis=1)
    vec = _vec_col(
        X, names=["c", "c", "x"], groups=["c", "c", None],
        indicators=["a", "b", None])
    model = _fit(SanityChecker(), _label(y), vec)
    assert model.indices == [2]  # both group columns dropped via Cramér's V
    stats = model.summary["stats"]
    assert stats[0]["cramersV"] == pytest.approx(1.0, abs=0.01)


def test_keeps_everything_when_clean():
    rng = np.random.default_rng(2)
    n = 300
    y = (rng.uniform(size=n) > 0.5).astype(float)
    X = rng.normal(size=(n, 4)).astype(np.float32)
    model = _fit(SanityChecker(), _label(y), _vec_col(X))
    assert model.indices == [0, 1, 2, 3]


def test_never_drops_all():
    n = 100
    y = np.zeros(n)
    X = np.ones((n, 2), dtype=np.float32)  # all constant
    model = _fit(SanityChecker(), _label(y), _vec_col(X))
    assert model.indices == [0, 1]  # retained despite flags


def test_cramers_v_function():
    # perfect association → 1
    assert cramers_v(np.array([[50, 0], [0, 50]])) == pytest.approx(1.0)
    # independence → 0
    assert cramers_v(np.array([[25, 25], [25, 25]])) == pytest.approx(0.0)
    assert cramers_v(np.zeros((2, 2))) == 0.0


def test_min_variance_filter():
    rng = np.random.default_rng(3)
    n = 200
    X = np.stack([rng.normal(size=n), np.full(n, 7.0)], axis=1)
    vf = FeatureGeneratorStage(name="v", ftype=t.OPVector).get_output()
    est = MinVarianceFilter().set_input(vf)
    model = est.fit([_vec_col(X)], FitContext(n))
    assert model.indices == [0]
    out = model.transform([_vec_col(X)])
    assert np.asarray(out.data).shape == (n, 1)


class TestReferenceDepth:
    """VERDICT r1 #7: Spearman, feature-feature corr, PMI/MI/rule
    confidence, sampling."""

    def _fit(self, X, y, meta=None, **kw):
        from transmogrifai_tpu.automl.sanity_checker import SanityChecker
        from transmogrifai_tpu.data.columns import Column
        import transmogrifai_tpu.types as T
        lcol = Column(T.RealNN, {"value": y.astype(np.float64),
                                 "mask": np.ones(len(y), dtype=bool)})
        vcol = Column(T.OPVector, X.astype(np.float32), meta=meta)
        est = SanityChecker(**kw)
        return est.fit_model([lcol, vcol], FitContext(n_rows=len(y)))

    def test_duplicated_column_dropped_by_feature_corr(self, rng):
        n = 300
        x = rng.normal(size=n)
        y = (x + rng.normal(0, 1, size=n) > 0).astype(float)
        X = np.stack([x, rng.normal(size=n), x * 1.0], axis=1)  # col2 = col0
        model = self._fit(X, y)
        assert model.indices == [0, 1]  # the LATER duplicate dropped
        reasons = model.summary["stats"][2]["dropped"]
        assert any("corr" in r and "col_0" in r for r in reasons), reasons

    def test_spearman_detects_monotone_nonlinear(self, rng):
        n = 400
        x = rng.uniform(size=n)
        y = np.exp(6 * x)  # monotone but very non-linear
        X = np.stack([x, rng.normal(size=n)], axis=1)
        pear = self._fit(X, y, correlation_type="pearson",
                         max_feature_corr=1.0)
        spear = self._fit(X, y, correlation_type="spearman",
                          max_feature_corr=1.0)
        sp = spear.summary["stats"][0]["corrLabel"]
        pe = pear.summary["stats"][0]["corrLabel"]
        assert sp > 0.99            # rank corr is exactly monotone
        assert pe < 0.95            # pearson understates it
        assert spear.summary["correlationType"] == "spearman"

    def test_rule_confidence_drop(self, rng):
        from transmogrifai_tpu.data.metadata import (
            VectorColumnMetadata, VectorMetadata)
        n = 200
        y = (np.arange(n) % 2).astype(float)
        # one-hot "level A" column that PERFECTLY implies label 1
        a = (y == 1.0).astype(np.float32)
        b = rng.normal(size=n).astype(np.float32)
        X = np.stack([a, 1.0 - a, b], axis=1)
        meta = VectorMetadata("v", (
            VectorColumnMetadata("cat", "PickList", grouping="cat",
                                 indicator_value="A"),
            VectorColumnMetadata("cat", "PickList", grouping="cat",
                                 indicator_value="B"),
            VectorColumnMetadata("num", "Real"),
        )).with_indices()
        model = self._fit(X, y, meta=meta, max_rule_confidence=0.9,
                          min_required_rule_support=0.1,
                          max_cramers_v=2.0,       # isolate the rule check
                          max_correlation=2.0, max_feature_corr=1.0)
        dropped = set(model.summary["dropped"])
        assert 0 in dropped and 1 in dropped  # the perfect-rule group
        assert 2 in model.indices
        cats = model.summary["categoricalStats"]
        assert cats and cats[0]["maxRuleConfidences"][0] == 1.0
        assert "pointwiseMutualInfo" in cats[0]
        assert cats[0]["mutualInfo"] > 0.5  # ~1 bit for a perfect predictor

    def test_sampling_limits(self, rng):
        n = 5000
        X = rng.normal(size=(n, 3))
        y = (X[:, 0] > 0).astype(float)
        model = self._fit(X, y, check_sample=0.2, sample_lower_limit=100,
                          max_feature_corr=1.0)
        s = model.summary
        assert s["n_rows"] == 1000  # 20% sample
        assert abs(s["sampleFraction"] - 0.2) < 1e-9
        # statistics still sound on the sample
        assert abs(s["stats"][0]["corrLabel"]) > 0.5


class TestWideFeatureAxis:
    """Blocked Gram path for wide X (SURVEY.md §5.7): no (d, d) matrix."""

    def test_blocked_matches_dense(self):
        from transmogrifai_tpu.automl.sanity_checker import (
            _corr_label_and_hits_blocked, _corr_matrix)
        import jax.numpy as jnp
        rng = np.random.default_rng(5)
        n, d = 300, 37
        X = rng.normal(size=(n, d)).astype(np.float32)
        X[:, 7] = X[:, 3] * 2.0 + 1e-6          # duplicate pair (7, 3)
        X[:, 20] = -X[:, 11]                     # anti-correlated pair
        y = (X[:, 0] > 0).astype(np.float32)
        corr_y, pairs = _corr_label_and_hits_blocked(
            jnp.asarray(X), jnp.asarray(y), thr=0.95, block=8)
        dense = _corr_matrix(jnp.asarray(
            np.concatenate([X, y[:, None]], axis=1)))
        np.testing.assert_allclose(corr_y, dense[:d, d], atol=1e-4)
        assert 7 in pairs and pairs[7][0][0] == 3
        assert 20 in pairs and pairs[20][0][0] == 11
        assert abs(pairs[7][0][1] - dense[7, 3]) < 1e-4
        # no spurious pairs beyond the two planted ones
        assert set(pairs) == {7, 20}

    def test_wide_duplicate_column_dropped(self, monkeypatch):
        import transmogrifai_tpu.automl.sanity_checker as sc_mod
        monkeypatch.setattr(sc_mod, "_WIDE_D", 16)  # force the wide path
        rng = np.random.default_rng(6)
        n, d = 400, 24
        X = rng.normal(size=(n, d)).astype(np.float32)
        X[:, 13] = X[:, 4]
        y = (X[:, 0] + rng.normal(0, 0.5, n) > 0).astype(np.float64)
        label = Column(t.RealNN, {"value": y, "mask": np.ones(n, bool)})
        vec = Column(t.OPVector, X)
        model = SanityChecker(max_feature_corr=0.99).fit_model(
            [label, vec], FitContext(n_rows=n, seed=0))
        kept = model.indices
        assert 4 in kept and 13 not in kept  # later duplicate dropped
        reasons = model.summary["stats"][13]["dropped"]
        assert any("corr" in r for r in reasons)
