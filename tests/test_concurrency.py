"""Tests for the whole-program concurrency auditor
(`analysis/concurrency.py`), its fixture corpus
(`tests/fixtures/conc/`), the L019 blocking-under-lock lint rule, and
the shared finding envelope (`analysis/report.py`).

The auditor allowlists everything under ``tests/`` (fixtures must never
pollute the repo audit), so fixture files are read from disk and fed
through ``audit_source`` under a neutral synthetic path.
"""

import json
import os

import pytest

from transmogrifai_tpu.analysis import concurrency as C
from transmogrifai_tpu.analysis import lint as L
from transmogrifai_tpu.analysis import report

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures", "conc")


def fixture_src(name):
    with open(os.path.join(FIXTURES, name), encoding="utf-8") as f:
        return f.read()


def audit_fixture(name, baseline=()):
    # a synthetic non-tests path: fixtures are allowlisted at their real
    # location by design
    return C.audit_source(fixture_src(name), f"conc_fix/{name}",
                          baseline=baseline)


def gating_rules(result):
    return sorted(f.rule for f in result.gating)


# --------------------------------------------------------------------------- #
# C001: sometimes-guarded attribute writes                                    #
# --------------------------------------------------------------------------- #

def test_racy_fixture_fires_c001():
    result = audit_fixture("racy.py")
    assert gating_rules(result) == ["C001"]
    (f,) = result.gating
    assert f.symbol == "Racy._count"
    assert "Racy._lock" in f.message
    # both roles that can touch the attribute are named
    assert "racy-worker" in f.message and "callers:Racy" in f.message


def test_racy_finding_points_at_the_bare_write():
    result = audit_fixture("racy.py")
    (f,) = result.gating
    src_lines = fixture_src("racy.py").splitlines()
    assert "self._count = 0" in src_lines[f.line - 1]


def test_clean_fixture_has_no_findings():
    result = audit_fixture("clean.py")
    assert result.gating == []
    # consistent _lock -> _aux nesting makes an edge but never a cycle
    assert result.cycles == []
    assert any(e["to"].endswith("Clean._aux") for e in result.lock_edges)


def test_c001_needs_two_roles():
    # mixed guarded/bare writes, but _count is only ever touched from
    # the worker closure — one role, no race, no finding
    src = """
import threading

class Solo:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def start(self):
        threading.Thread(target=self._worker, name="solo-w").start()

    def _worker(self):
        with self._lock:
            self._count += 1
        self._bump()

    def _bump(self):
        self._count = 0
"""
    result = C.audit_source(src, "conc_fix/solo.py")
    assert gating_rules(result) == []


def test_construction_phase_writes_are_exempt():
    # bare writes inside a helper only __init__ reaches are published by
    # Thread.start()'s happens-before — not a C001
    src = """
import threading

class Lazy:
    def __init__(self):
        self._lock = threading.Lock()
        self._load()

    def _load(self):
        self._cache = {}

    def start(self):
        threading.Thread(target=self._worker, name="lazy-w").start()

    def _worker(self):
        with self._lock:
            self._cache = {}

    def snapshot(self):
        with self._lock:
            self._cache = dict(self._cache)
"""
    result = C.audit_source(src, "conc_fix/lazy.py")
    assert gating_rules(result) == []


# --------------------------------------------------------------------------- #
# C002: lock-order cycles                                                     #
# --------------------------------------------------------------------------- #

def test_deadlock_fixture_fires_c002_with_full_path():
    result = audit_fixture("deadlock.py")
    assert "C002" in gating_rules(result)
    assert len(result.cycles) == 1
    (f,) = [f for f in result.gating if f.rule == "C002"]
    # the full cycle path: both legs with their acquisition sites
    assert "Ledger._ledger_lock -> Ledger._audit_lock" in f.message
    assert "Ledger._audit_lock -> Ledger._ledger_lock" in f.message
    assert "transfer_out" in f.message and "transfer_in" in f.message


def test_deadlock_fixture_graph_summary_prints_cycle():
    result = audit_fixture("deadlock.py")
    text = C._graph_summary(result)
    assert "2 edge(s), 1 cycle(s)" in text
    assert "CYCLE:" in text


# --------------------------------------------------------------------------- #
# C003: blocking under a held lock                                            #
# --------------------------------------------------------------------------- #

def test_blocking_fixture_direct_sleep_under_lock():
    result = audit_fixture("blocking.py")
    direct = [f for f in result.gating
              if f.rule == "C003" and "time.sleep" in f.message]
    assert len(direct) == 1
    assert "Slow._lock" in direct[0].message


def test_blocking_fixture_interprocedural_io():
    result = audit_fixture("blocking.py")
    via = [f for f in result.gating
           if f.rule == "C003" and "reaches" in f.message]
    assert len(via) == 1
    assert "_flush" in via[0].message
    assert "file I/O (open)" in via[0].message


def test_condition_wait_is_not_blocking():
    # Condition.wait releases the lock while blocked — the waiter()
    # shape in the fixture must produce no C003
    result = audit_fixture("blocking.py")
    assert not any("wait" in f.message for f in result.gating)
    assert len([f for f in result.gating if f.rule == "C003"]) == 2


# --------------------------------------------------------------------------- #
# C004: generation-fence discipline                                           #
# --------------------------------------------------------------------------- #

def test_fence_fixture_flags_only_the_unfenced_write():
    result = audit_fixture("fence.py")
    assert gating_rules(result) == ["C004"]
    (f,) = result.gating
    assert f.symbol == "SlotPool._slots"
    src_lines = fixture_src("fence.py").splitlines()
    # the flagged line is inside fill_unfenced, not the re-checked fill
    above = "\n".join(src_lines[:f.line])
    assert "def fill_unfenced" in above
    assert above.rindex("def fill_unfenced") > above.rindex("def fill(")


def test_fence_owner_is_exempt():
    # advance() writes _slots too, but it OWNS the generation (it is
    # the restart); only gen-readers owe a re-check
    result = audit_fixture("fence.py")
    src_lines = fixture_src("fence.py").splitlines()
    for f in result.gating:
        assert "advance" not in src_lines[f.line - 1]


# --------------------------------------------------------------------------- #
# Suppression: annotations and the reviewed baseline                          #
# --------------------------------------------------------------------------- #

def test_guarded_by_def_annotation_marks_writes_guarded():
    # _apply carries `# guarded-by: _lock` on its def line: its writes
    # count as guarded, so the only C001 is reset()'s bare write
    result = audit_fixture("annotated.py")
    c001 = [f for f in result.findings if f.rule == "C001"]
    assert len(c001) == 1


def test_conc_ok_annotation_suppresses_but_reports():
    result = audit_fixture("annotated.py")
    (f,) = [f for f in result.findings if f.rule == "C001"]
    assert f.suppression == "annotation"
    assert result.gating == []


def test_baseline_suppresses_by_symbol_not_line():
    baseline = [{"file": "conc_fix/racy.py", "rule": "C001",
                 "symbol": "Racy._count", "reason": "fixture"}]
    result = audit_fixture("racy.py", baseline=baseline)
    assert result.gating == []
    (f,) = [f for f in result.findings if f.rule == "C001"]
    assert f.suppression == "baseline"


def test_baseline_wrong_symbol_does_not_suppress():
    baseline = [{"file": "conc_fix/racy.py", "rule": "C001",
                 "symbol": "Racy._other", "reason": "stale entry"}]
    result = audit_fixture("racy.py", baseline=baseline)
    assert gating_rules(result) == ["C001"]


def test_parse_error_is_a_warning_not_gating():
    result = C.audit_source("def broken(:\n", "conc_fix/broken.py")
    assert result.gating == []
    (f,) = result.findings
    assert f.rule == "C000" and f.severity == "warning"


# --------------------------------------------------------------------------- #
# The repo itself + runtime budget                                            #
# --------------------------------------------------------------------------- #

def test_repo_audits_clean_with_reviewed_baseline():
    baseline = C.load_baseline(os.path.join(REPO, "conc_baseline.json"))
    result = C.audit_paths([os.path.join(REPO, "transmogrifai_tpu")],
                           baseline=baseline)
    assert result.gating == [], "\n".join(str(f) for f in result.gating)
    assert result.cycles == []
    # the make conc-check budget: whole-repo audit stays under 10s
    assert result.elapsed_s < 10.0
    # sanity: the audit actually saw the fleet (roles + locks exist)
    assert len(result.roles) >= 5
    assert result.n_locks >= 10


# --------------------------------------------------------------------------- #
# CLI                                                                         #
# --------------------------------------------------------------------------- #

def test_cli_missing_path_exits_2(capsys):
    assert C.main(["/nonexistent/nowhere"]) == 2
    assert "does not exist" in capsys.readouterr().err


def test_cli_json_envelope(tmp_path, capsys):
    p = tmp_path / "racy_case.py"
    p.write_text(fixture_src("racy.py"), encoding="utf-8")
    rc = C.main([str(p), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["tool"] == "concurrency" and doc["version"] == 1
    (finding,) = doc["findings"]
    assert finding["rule"] == "C001"
    assert set(finding) >= {"file", "line", "rule", "severity",
                            "message", "suppression"}
    assert doc["counts"]["error"] == 1


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    p = tmp_path / "racy_case.py"
    p.write_text(fixture_src("racy.py"), encoding="utf-8")
    bl = tmp_path / "baseline.json"
    assert C.main([str(p), "--baseline", str(bl),
                   "--write-baseline"]) == 0
    capsys.readouterr()
    # the grandfathered baseline makes the same audit pass
    assert C.main([str(p), "--baseline", str(bl)]) == 0
    out = capsys.readouterr().out
    assert "0 gating finding(s), 1 suppressed" in out


# --------------------------------------------------------------------------- #
# L019: blocking-under-lock lint + shared envelope                            #
# --------------------------------------------------------------------------- #

_L019_SRC = """
import threading
import time

LOCK = threading.Lock()

def throttle():
    with LOCK:
        time.sleep(1.0)
"""


def test_lint_l019_flags_sleep_under_lock():
    findings = [f for f in L.lint_source(_L019_SRC, "svc.py")
                if f.code == "L019"]
    assert len(findings) == 1
    assert findings[0].gating
    assert "time.sleep" in findings[0].message


def test_lint_l019_flags_file_io_under_lock():
    src = _L019_SRC.replace("time.sleep(1.0)",
                            'open("/tmp/x", "w").write("x")')
    codes = {f.code for f in L.lint_source(src, "svc.py")}
    assert "L019" in codes


def test_lint_l019_allowlists_smoke_and_tests():
    for path in ("ingest_smoke.py", "chaos.py",
                 os.path.join("tests", "test_x.py")):
        assert not any(f.code == "L019"
                       for f in L.lint_source(_L019_SRC, path))


def test_lint_l019_conc_ok_annotation_suppresses():
    src = _L019_SRC.replace(
        "        time.sleep(1.0)",
        "        # conc-ok: C003 (deliberate pacing)\n"
        "        time.sleep(1.0)")
    (f,) = [f for f in L.lint_source(src, "svc.py") if f.code == "L019"]
    assert f.suppression == "annotation"
    assert not f.gating


def test_lint_parse_failure_is_warning_and_exits_zero(tmp_path):
    findings = L.lint_source("def broken(:\n", "bad.py")
    assert [f.code for f in findings] == ["L000"]
    assert findings[0].severity == "warning"
    assert not findings[0].gating
    p = tmp_path / "bad.py"
    p.write_text("def broken(:\n", encoding="utf-8")
    assert L.main([str(p)]) == 0  # parse-skips never gate the CLI


def test_lint_cli_gates_on_real_findings(tmp_path, capsys):
    p = tmp_path / "svc.py"
    p.write_text(_L019_SRC, encoding="utf-8")
    assert L.main([str(p)]) == 1
    capsys.readouterr()
    rc = L.main([str(p), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["tool"] == "lint" and doc["version"] == 1
    assert doc["counts"]["error"] == 1
    # one envelope shape across both analyzers
    assert set(doc["findings"][0]) == {"file", "line", "rule",
                                       "severity", "message",
                                       "suppression"}


def test_shared_envelope_counts():
    findings = [
        report.Finding("a.py", 1, "C001", "m"),
        report.Finding("a.py", 2, "C003", "m", suppression="annotation"),
        report.Finding("b.py", 3, "L000", "m", severity="warning"),
    ]
    doc = json.loads(report.render_json("lint", findings))
    assert doc["counts"] == {"error": 1, "warning": 1, "suppressed": 1}
    assert len(report.gating(findings)) == 1
