"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's `local[2]` in-process Spark trick
(`utils/.../test/TestSparkContext.scala:36-80`): "distributed" behavior is
tested on local virtual devices — here via XLA's host-platform device count,
so every sharding/collective path is exercised without a TPU pod.
"""

import os

# NOTE: the axon TPU plugin ignores the JAX_PLATFORMS env var; forcing CPU
# requires jax.config.update (or JAX_PLATFORM_NAME) before backend init.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Learned cost model (perf/): OFF by default in the suite — tier-1
# sweeps/schedules must make bit-deterministic cold-start decisions and
# must not read or append to the developer's ~/.cache profile corpus.
# Tests that exercise the model opt back in with monkeypatch.setenv
# (perf.params.enabled() reads the env per call).
os.environ["TRANSMOGRIFAI_PERF_MODEL"] = "0"

# Crash flight recorder: serving tests trip breakers/watchdogs on
# purpose, and each incident dumps a post-mortem artifact — point the
# dump dir at a per-run temp location instead of the developer's
# ~/.cache (same hygiene rule as the perf corpus above).
import tempfile as _tempfile  # noqa: E402

os.environ.setdefault(
    "TRANSMOGRIFAI_FLIGHT_DIR",
    os.path.join(_tempfile.gettempdir(),
                 f"transmogrifai-flight-tests-{os.getpid()}"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The fast suite is compile-dominated (tree-fit programs at many shapes):
# the persistent XLA cache cuts `pytest tests/` from ~7.3 to ~3 min on
# every run after the first. Executables are keyed by HLO + backend
# version, so this stays hermetic; a separate dir keeps CPU test
# artifacts apart from the TPU runtime cache.
from transmogrifai_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache(os.path.expanduser(
    "~/.cache/transmogrifai_tpu/xla-cache-cputests"))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_uids():
    """Deterministic uids per test (reference resets UID in test fixtures)."""
    from transmogrifai_tpu.utils import uid
    uid.reset()
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(42)
