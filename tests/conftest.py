"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's `local[2]` in-process Spark trick
(`utils/.../test/TestSparkContext.scala:36-80`): "distributed" behavior is
tested on local virtual devices — here via XLA's host-platform device count,
so every sharding/collective path is exercised without a TPU pod.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_uids():
    """Deterministic uids per test (reference resets UID in test fixtures)."""
    from transmogrifai_tpu.utils import uid
    uid.reset()
    yield


@pytest.fixture
def rng():
    return np.random.default_rng(42)
