"""continual/ subsystem: crash-consistent store append (+ feature-cache
invalidation), training fingerprint + PSI drift detection, warm-start
refits (no-op parity, fewer-steps convergence, compiled-program reuse),
and the closed loop's gated promotion / journal resume / auto-rollback."""

import json
import os
import threading

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.continual import (
    ContinualLoop, ContinualParams, DriftMonitor, TrainingFingerprint,
    extract_warm_params, load_fingerprint, prepare_warm_estimator, psi)
from transmogrifai_tpu.data.columnar_store import (
    ColumnarStore, StoreIntegrityError, synth_binary_store)
from transmogrifai_tpu.data.dataset import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models.logistic import (
    OpLogisticRegression, fit_logreg)
from transmogrifai_tpu.ops.numeric import RealVectorizer
from transmogrifai_tpu.stages.base import FitContext
from transmogrifai_tpu.workflow import Workflow

import jax.numpy as jnp

D = 6


def _linear_data(n, seed=0, shift=0.0, d=D):
    rng = np.random.default_rng(seed)
    beta = np.random.default_rng(99).normal(size=d)  # fixed relationship
    X = (rng.standard_normal((n, d)) + shift).astype(np.float32)
    y = (X @ beta > 0).astype(np.float32)
    return X, y


def _make_store(path, n=1200, seed=0):
    X, y = _linear_data(n, seed=seed)
    w = ColumnarStore.create(str(path), n, D, dtype="float32")
    w.write_chunk(0, X, y)
    return w.close(), X, y


# --------------------------------------------------------------------- #
# streaming append                                                       #
# --------------------------------------------------------------------- #

class TestAppend:
    def test_append_extends_rows_and_reads_span_segments(self, tmp_path):
        st, X, y = _make_store(tmp_path / "s", n=1000)
        Xn, yn = _linear_data(300, seed=1)
        w = ColumnarStore.append(st.path, 300)
        w.write_chunk(0, Xn, yn)
        st2 = w.close()
        assert st2.n_rows == 1300 and st2.base_rows == 1000
        np.testing.assert_array_equal(st2.chunk(0, 1000), X)
        np.testing.assert_array_equal(st2.chunk(1000, 1300), Xn)
        span = st2.chunk(900, 1100)  # crosses the segment boundary
        np.testing.assert_array_equal(span[:100], X[900:])
        np.testing.assert_array_equal(span[100:], Xn[:100])
        yfull = np.asarray(st2.y)
        np.testing.assert_array_equal(yfull[:1000], y)
        np.testing.assert_array_equal(yfull[1000:], yn)
        assert sum(len(c) for _, c in st2.iter_chunks(256)) == 1300

    def test_append_verifies_and_checksums_cover_segments(self, tmp_path):
        st, _, _ = _make_store(tmp_path / "s", n=600)
        Xn, yn = _linear_data(200, seed=2)
        w = ColumnarStore.append(st.path, 200)
        w.write_chunk(0, Xn, yn)
        st2 = w.close()
        # full checksum verify passes, and the manifest records per-file
        # checksums for the segment columns
        st3 = ColumnarStore(st.path)  # verify=True re-hashes everything
        seg_keys = [k for k in st3.meta["checksums"] if k.startswith("seg-")]
        assert len(seg_keys) == 2  # X.bin + y.bin of the segment
        # bit-flip the segment matrix: open() must refuse
        seg_x = os.path.join(st.path, st2.meta["segments"][0]["dir"],
                             "X.bin")
        with open(seg_x, "r+b") as fh:
            fh.seek(8)
            b = fh.read(1)
            fh.seek(8)
            fh.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(StoreIntegrityError):
            ColumnarStore(st.path)

    def test_interrupted_append_leaves_previous_store_intact(self, tmp_path):
        st, X, _ = _make_store(tmp_path / "s", n=500)
        Xn, yn = _linear_data(100, seed=3)
        w = ColumnarStore.append(st.path, 100)
        w.write_chunk(0, Xn, yn)
        # kill BEFORE close(): no manifest update, no committed segment
        del w
        st2 = ColumnarStore(st.path)
        assert st2.n_rows == 500
        np.testing.assert_array_equal(st2.chunk(0, 500), X)
        # the next append still works and lands cleanly
        w2 = ColumnarStore.append(st.path, 100)
        w2.write_chunk(0, Xn, yn)
        assert w2.close().n_rows == 600

    def test_sample_rows_gathers_across_segments(self, tmp_path):
        st, X, _ = _make_store(tmp_path / "s", n=400)
        Xn, yn = _linear_data(200, seed=4)
        w = ColumnarStore.append(st.path, 200)
        w.write_chunk(0, Xn, yn)
        st2 = w.close()
        sample = st2.sample_rows(600, seed=0)  # all rows, sorted order
        full = np.concatenate([X, Xn]).astype(np.float32)
        np.testing.assert_allclose(sample, full, rtol=1e-6)

    def test_concurrent_appends_lose_no_rows(self, tmp_path):
        """Appenders that all opened against the SAME manifest snapshot
        commit serially against a re-read manifest: every segment lands,
        no rows lost, full checksum verification passes."""
        st, X, _ = _make_store(tmp_path / "s", n=400)
        batches = [_linear_data(50, seed=60 + i) for i in range(6)]
        writers = []
        for Xb, yb in batches:  # all open BEFORE any commit
            w = ColumnarStore.append(st.path, 50)
            w.write_chunk(0, Xb, yb)
            writers.append(w)
        threads = [threading.Thread(target=w.close) for w in writers]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        st2 = ColumnarStore(st.path)  # full verify
        assert st2.n_rows == 400 + 300
        assert len(st2.meta["segments"]) == 6
        appended = np.asarray(st2.chunk(400, 700))
        want = np.concatenate([b[0] for b in batches])
        # commit order is scheduler-dependent: compare as row multisets
        np.testing.assert_array_equal(
            np.sort(appended.view([("", appended.dtype)] * D), axis=0),
            np.sort(want.view([("", want.dtype)] * D), axis=0))

    def test_take_rows_bounds_and_negative_indices(self, tmp_path):
        """Numpy fancy-index parity: negatives count from the end,
        out-of-range raises — never an uninitialized gather buffer."""
        st, X, _ = _make_store(tmp_path / "s", n=300)
        Xn, yn = _linear_data(100, seed=5)
        w = ColumnarStore.append(st.path, 100)
        w.write_chunk(0, Xn, yn)
        st2 = w.close()
        np.testing.assert_array_equal(st2.take_rows(np.array([-1])),
                                      Xn[-1:])
        np.testing.assert_array_equal(st2.take_rows(np.array([-400])),
                                      X[:1])
        with pytest.raises(IndexError):
            st2.take_rows(np.array([400]))
        with pytest.raises(IndexError):
            st2.take_rows(np.array([0, -401]))

    def test_append_invalidates_feature_cache_never_stale_hit(
            self, tmp_path):
        """Satellite regression: a post-append device_matrix with
        cache="readwrite" must be a MISS (the fingerprint moved), and
        the rebuilt buffer must contain the appended rows."""
        from transmogrifai_tpu.data import feature_cache as fc
        from transmogrifai_tpu.parallel import bigdata as bd
        st = synth_binary_store(str(tmp_path / "s"), 2000, 8, seed=5,
                                chunk_rows=512)
        params = fc.FeatureCacheParams(dir=str(tmp_path / "cache"),
                                       policy="readwrite")
        x1, st1 = bd.device_matrix(st, chunk_rows=512, cache=params,
                                   return_stats=True)
        x2, st2 = bd.device_matrix(st, chunk_rows=512, cache=params,
                                   return_stats=True)
        assert (st1.cache, st2.cache) == ("miss", "hit")
        Xn = np.random.default_rng(0).standard_normal((512, 8)) \
            .astype(np.float16)
        w = ColumnarStore.append(st.path, 512)
        w.write_chunk(0, Xn, np.zeros(512, np.float32))
        st_post = w.close()
        x3, st3 = bd.device_matrix(st_post, chunk_rows=512, cache=params,
                                   return_stats=True)
        assert st3.cache == "miss", "post-append build served stale bytes"
        assert x3.shape[0] >= 2512
        np.testing.assert_allclose(
            np.asarray(x3[2000:2512], np.float32),
            Xn.astype(np.float32), rtol=1e-2, atol=1e-2)
        # and the post-append key is itself cacheable: rebuild hits
        _, st4 = bd.device_matrix(st_post, chunk_rows=512, cache=params,
                                  return_stats=True)
        assert st4.cache == "hit"


# --------------------------------------------------------------------- #
# fingerprint + drift monitor                                            #
# --------------------------------------------------------------------- #

class TestDrift:
    def test_psi_zero_for_identical_and_positive_for_shifted(self):
        p = np.array([0.2, 0.3, 0.5])
        assert psi(p, p) == pytest.approx(0.0, abs=1e-9)
        assert psi(p, np.array([0.5, 0.3, 0.2])) > 0.1

    def test_fingerprint_roundtrip(self):
        X, y = _linear_data(800, seed=6)
        fp = TrainingFingerprint.from_arrays(X, y, n_bins=8,
                                             feature_names=[f"f{i}"
                                                            for i in
                                                            range(D)])
        fp2 = TrainingFingerprint.from_json(
            json.loads(json.dumps(fp.to_json())))
        assert fp2.n_rows == 800 and fp2.n_bins == 8
        np.testing.assert_allclose(fp2.fractions, fp.fractions)
        np.testing.assert_allclose(fp2.edges, fp.edges)
        assert fp2.label_rate == pytest.approx(fp.label_rate)

    def test_monitor_quiet_on_same_distribution(self):
        X, y = _linear_data(2000, seed=7)
        fp = TrainingFingerprint.from_arrays(X, y)
        mon = DriftMonitor(fp, ContinualParams(min_window_rows=256))
        Xn, yn = _linear_data(600, seed=8)  # same distribution
        mon.observe(Xn, yn)
        rep = mon.check()
        assert not rep.drifted and rep.max_psi < 0.1, rep.to_json()

    def test_monitor_fires_on_feature_shift(self):
        X, y = _linear_data(2000, seed=9)
        fp = TrainingFingerprint.from_arrays(X, y)
        mon = DriftMonitor(fp, ContinualParams(min_window_rows=256))
        Xn, yn = _linear_data(600, seed=10, shift=2.0)
        mon.observe(Xn, yn)
        rep = mon.check()
        assert rep.drifted and rep.max_psi > 0.2
        assert rep.triggers, rep.to_json()

    def test_monitor_fires_on_label_shift_alone(self):
        X, y = _linear_data(2000, seed=11)
        fp = TrainingFingerprint.from_arrays(X, y)
        mon = DriftMonitor(fp, ContinualParams(min_window_rows=100))
        Xn, _ = _linear_data(400, seed=12)
        mon.observe(Xn, np.ones(400))  # all-positive labels
        rep = mon.check()
        assert rep.drifted and "__label__" in rep.triggers

    def test_monitor_respects_min_window_and_trims(self):
        X, y = _linear_data(1000, seed=13)
        fp = TrainingFingerprint.from_arrays(X, y)
        mon = DriftMonitor(fp, ContinualParams(window_rows=512,
                                               min_window_rows=256))
        Xs, ys = _linear_data(100, seed=14, shift=5.0)
        mon.observe(Xs, ys)
        assert not mon.check().drifted  # below min_window_rows
        for s in range(10):
            Xn, yn = _linear_data(128, seed=20 + s)
            mon.observe(Xn, yn)
        assert mon.window_rows <= 512 + 128  # trimmed to the window

    def test_monitor_concurrent_observe_and_check(self):
        """The documented deployment: the application thread appends
        (observe) while the supervisor thread checks — the window must
        stay a consistent (X, y) pairing, never racing the deque."""
        X, y = _linear_data(1500, seed=40)
        fp = TrainingFingerprint.from_arrays(X, y)
        mon = DriftMonitor(fp, ContinualParams(window_rows=512,
                                               min_window_rows=64))
        Xb, yb = _linear_data(64, seed=41)
        stop = threading.Event()
        errors = []

        def feeder():
            while not stop.is_set():
                try:
                    mon.observe(Xb, yb)
                except Exception as e:  # pragma: no cover - the bug
                    errors.append(e)
                    return

        th = threading.Thread(target=feeder, daemon=True)
        th.start()
        try:
            for _ in range(200):
                Xw, yw = mon.window()
                assert len(Xw) == len(yw)
                mon.check()
        finally:
            stop.set()
            th.join(timeout=10)
        assert not errors, errors

    def test_fingerprint_persisted_via_train_and_loaded(self, tmp_path):
        X, y = _linear_data(600, seed=15)
        cols = {f"f{j}": X[:, j].astype(np.float64) for j in range(D)}
        cols["label"] = y.astype(np.float64)
        schema = {f"f{j}": t.Real for j in range(D)}
        schema["label"] = t.Integral
        ds = Dataset(cols, schema)
        preds, label = FeatureBuilder.from_dataset(ds, response="label")
        vec = RealVectorizer(track_nulls=False).set_input(*preds) \
            .get_output()
        pred = OpLogisticRegression(max_iter=30).set_input(
            label, vec).get_output()
        model = Workflow().set_result_features(pred, label) \
            .set_input_dataset(ds) \
            .set_parameters({"continual": {}}).train()
        assert model.training_fingerprint is not None
        assert model.training_fingerprint.n_features == D
        # capture is opt-in: a batch train without the continual block
        # must not pay the fingerprint pass
        plain = Workflow().set_result_features(pred, label) \
            .set_input_dataset(ds).train()
        assert plain.training_fingerprint is None
        ins = model.model_insights().to_json()
        assert ins["trainingFingerprint"]["n_rows"] == 600
        model.save(str(tmp_path / "m"),
                   extra_json={"insights.json": ins})
        fp = load_fingerprint(str(tmp_path / "m"))
        assert fp is not None and fp.n_features == D

    def test_extra_json_is_integrity_covered(self, tmp_path):
        from transmogrifai_tpu.workflow.serialization import (
            ModelIntegrityError, verify_model_dir)
        X, y = _linear_data(200, seed=16)
        cols = {f"f{j}": X[:, j].astype(np.float64) for j in range(D)}
        cols["label"] = y.astype(np.float64)
        schema = {f"f{j}": t.Real for j in range(D)}
        schema["label"] = t.Integral
        ds = Dataset(cols, schema)
        preds, label = FeatureBuilder.from_dataset(ds, response="label")
        vec = RealVectorizer(track_nulls=False).set_input(*preds) \
            .get_output()
        pred = OpLogisticRegression(max_iter=10).set_input(
            label, vec).get_output()
        model = Workflow().set_result_features(pred, label) \
            .set_input_dataset(ds).train()
        path = str(tmp_path / "m")
        model.save(path, extra_json={"insights.json": {"k": 1}})
        verify_model_dir(path)
        with open(os.path.join(path, "insights.json"), "a") as fh:
            fh.write(" ")  # tamper
        with pytest.raises(ModelIntegrityError):
            verify_model_dir(path)


# --------------------------------------------------------------------- #
# warm-start refits                                                      #
# --------------------------------------------------------------------- #

def _holdout_logloss(params, X, y):
    import jax
    logits = X @ np.asarray(params["W"]) + np.asarray(params["b"])
    p = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    idx = y.astype(int)
    return -float(np.mean(np.log(np.clip(p[np.arange(len(y)), idx],
                                         1e-12, 1.0))))


class TestWarmStart:
    def test_warm_fit_on_unchanged_data_is_noop_within_tolerance(self):
        X, y = _linear_data(800, seed=17)
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        w = jnp.ones(len(y))
        cold = fit_logreg(Xj, yj, w, jnp.float32(0.01), 2, 80)
        warm = fit_logreg(Xj, yj, w, jnp.float32(0.01), 2, 5,
                          init_params=cold)
        assert float(jnp.abs(warm["W"] - cold["W"]).max()) < 1e-2
        assert float(jnp.abs(warm["b"] - cold["b"]).max()) < 1e-2

    def test_warm_fit_reaches_cold_metric_in_strictly_fewer_steps(self):
        """Satellite: on appended data the warm start must hit the
        cold fit's holdout metric with a strictly smaller optimizer
        step count (counts asserted)."""
        X, y = _linear_data(900, seed=18)
        Xa, ya = _linear_data(500, seed=19, shift=1.0)  # appended delta
        Xh, yh = _linear_data(400, seed=20, shift=1.0)  # holdout
        X2 = np.concatenate([X, Xa])
        y2 = np.concatenate([y, ya])
        Xj, yj = jnp.asarray(X2), jnp.asarray(y2)
        w = jnp.ones(len(y2))
        # resident weights: converged on the PRE-append data
        resident = fit_logreg(jnp.asarray(X), jnp.asarray(y),
                              jnp.ones(len(y)), jnp.float32(0.01), 2, 80)
        # target: a converged cold fit's holdout loss (+2% slack)
        ref = fit_logreg(Xj, yj, w, jnp.float32(0.01), 2, 120)
        target = _holdout_logloss(ref, Xh, yh) * 1.02

        def steps_to_target(init):
            for steps in (2, 4, 8, 16, 32, 64, 128):
                p = fit_logreg(Xj, yj, w, jnp.float32(0.01), 2, steps,
                               init_params=init)
                if _holdout_logloss(p, Xh, yh) <= target:
                    return steps
            return 256

        cold_steps = steps_to_target(None)
        warm_steps = steps_to_target(resident)
        assert warm_steps < cold_steps, (warm_steps, cold_steps)

    def test_warm_fit_reuses_compiled_program(self):
        """Two warm refits at the same shapes = ONE compiled program:
        the jit cache must not grow between them."""
        X, y = _linear_data(300, seed=21)
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        w = jnp.ones(len(y))
        p0 = fit_logreg(Xj, yj, w, jnp.float32(0.01), 2, 10)
        fit_logreg(Xj, yj, w, jnp.float32(0.01), 2, 10, init_params=p0)
        size_after_first_warm = fit_logreg._cache_size()
        p1 = fit_logreg(Xj, yj, w, jnp.float32(0.02), 2, 10,
                        init_params=p0)
        fit_logreg(Xj, yj, w, jnp.float32(0.01), 2, 10, init_params=p1)
        assert fit_logreg._cache_size() == size_after_first_warm

    def test_warm_shape_mismatch_fails_loudly(self):
        X, y = _linear_data(200, seed=22)
        est = OpLogisticRegression(max_iter=5)
        with pytest.raises(ValueError, match="shape"):
            est.fit_arrays(jnp.asarray(X), jnp.asarray(y),
                           jnp.ones(len(y)), FitContext(len(y), 0),
                           init_params={"W": np.zeros((D + 1, 2)),
                                        "b": np.zeros(2)})

    def test_gbt_warm_appends_rounds_and_forest_replaces_oldest(self):
        from transmogrifai_tpu.models.trees import (
            OpGBTClassifier, OpRandomForestClassifier)
        X, y = _linear_data(500, seed=23)
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        w = jnp.ones(len(y))
        ctx = FitContext(len(y), 1)
        gbt = OpGBTClassifier(n_estimators=8, max_depth=3)
        g1 = gbt.fit_arrays(Xj, yj, w, ctx)
        gbt2 = OpGBTClassifier(n_estimators=8, max_depth=3)
        assert prepare_warm_estimator(gbt2, g1)
        g2 = gbt2.fit_arrays(Xj, yj, w, ctx)
        assert g2.trees["feat"].shape[0] == 8 + 2  # n_estimators // 4
        np.testing.assert_array_equal(g2.trees["feat"][:8],
                                      g1.trees["feat"])
        rf = OpRandomForestClassifier(n_trees=10, max_depth=3)
        r1 = rf.fit_arrays(Xj, yj, w, ctx)
        rf2 = OpRandomForestClassifier(n_trees=10, max_depth=3)
        assert prepare_warm_estimator(rf2, r1, delta_rows=100)
        r2 = rf2.fit_arrays(Xj, yj, w, ctx)
        n_new = max(1, round(10 * 100 / 500))
        assert r2.trees["feat"].shape[0] == 10  # size preserved
        np.testing.assert_array_equal(r2.trees["feat"][:10 - n_new],
                                      r1.trees["feat"][n_new:])

    def test_gbt_warm_growth_is_capped_at_twice_the_budget(self):
        """An always-on loop must not boost the ensemble without bound:
        growth clamps to the 2x ceiling, and a resident ensemble AT the
        ceiling falls back to a cold fit (size resets)."""
        from transmogrifai_tpu.models.trees import OpGBTClassifier
        X, y = _linear_data(400, seed=27)
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        w = jnp.ones(len(y))
        ctx = FitContext(len(y), 1)
        gbt = OpGBTClassifier(n_estimators=4, max_depth=3)
        m = gbt.fit_arrays(Xj, yj, w, ctx)
        for _ in range(12):  # far more cycles than the cap allows rounds
            nxt = OpGBTClassifier(n_estimators=4, max_depth=3)
            assert prepare_warm_estimator(nxt, m)
            m = nxt.fit_arrays(Xj, yj, w, ctx)
            assert m.trees["feat"].shape[0] <= 2 * 4
        assert m.trees["feat"].shape[0] <= 2 * 4

    def test_tree_warm_falls_back_cold_on_class_or_width_change(self):
        """Host-side warm validation for trees (the resolve_init_params
        analogue): a new class or feature width under the resident
        ensemble must fit cold, not silently mistrain (one_hot of an
        unseen class is all-zeros)."""
        from transmogrifai_tpu.models.trees import (
            OpRandomForestClassifier, warm_tree_compatible)
        X, y = _linear_data(300, seed=28)
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        w = jnp.ones(len(y))
        ctx = FitContext(len(y), 1)
        rf = OpRandomForestClassifier(n_trees=6, max_depth=3)
        m = rf.fit_arrays(Xj, yj, w, ctx)
        warm = extract_warm_params(m)
        assert warm_tree_compatible(warm, X, n_classes=2)
        assert not warm_tree_compatible(warm, X, n_classes=3)
        assert not warm_tree_compatible(warm, np.zeros((4, D + 1)))
        # end-to-end: appended data introduces a third class -> the
        # armed warm refit fits cold with 3-class leaves
        y3 = np.asarray(y).copy()
        y3[:10] = 2
        rf2 = OpRandomForestClassifier(n_trees=6, max_depth=3)
        assert prepare_warm_estimator(rf2, m)
        m3 = rf2.fit_arrays(Xj, jnp.asarray(y3), w, ctx)
        assert m3.trees["leaf"].shape[-1] == 3
        # a NARROWER estimator histogram than the resident edges would
        # silently drop rows binned past max_bins: must fit cold
        assert warm_tree_compatible(warm, X, max_bins=rf.max_bins)
        assert not warm_tree_compatible(warm, X,
                                        max_bins=rf.max_bins // 2)
        rf4 = OpRandomForestClassifier(n_trees=6, max_depth=3,
                                       max_bins=rf.max_bins // 2)
        assert prepare_warm_estimator(rf4, m)
        m4 = rf4.fit_arrays(Xj, yj, w, ctx)      # cold: own (narrow) edges
        assert m4.edges.shape[1] + 1 == rf4.max_bins

    def test_extract_warm_params_families(self):
        from transmogrifai_tpu.models.glm import GLMModel
        from transmogrifai_tpu.models.linear import LinearRegressionModel
        from transmogrifai_tpu.models.logistic import (
            LogisticRegressionModel)
        lm = LogisticRegressionModel(W=np.ones((3, 2)), b=np.zeros(2))
        assert set(extract_warm_params(lm)) == {"W", "b"}
        lin = LinearRegressionModel(beta=np.ones(3), intercept=0.5)
        assert set(extract_warm_params(lin)) == {"beta"}
        glm = GLMModel(beta=np.ones(3), b=0.1, family="poisson")
        assert set(extract_warm_params(glm)) == {"beta", "b"}
        assert extract_warm_params(object()) is None


# --------------------------------------------------------------------- #
# the closed loop                                                        #
# --------------------------------------------------------------------- #

def _loop_fixture(tmp_path, **param_kw):
    st, X, y = _make_store(tmp_path / "store", n=1200)
    params = ContinualParams(window_rows=800, min_window_rows=200,
                             journal_dir=str(tmp_path / "journal"),
                             **param_kw)
    loop = ContinualLoop(st, str(tmp_path / "model"), params=params,
                         seed=3)
    loop.train_initial()
    return loop


class TestLoop:
    def test_no_drift_cycle_is_a_noop(self, tmp_path):
        loop = _loop_fixture(tmp_path)
        assert loop.run_cycle()["status"] == "no_drift"

    def test_drift_refit_promotes_and_updates_monitor(self, tmp_path):
        loop = _loop_fixture(tmp_path)
        Xn, yn = _linear_data(500, seed=30, shift=2.0)
        loop.append(Xn, yn)
        r = loop.run_cycle()
        assert r["status"] == "promoted", r
        assert r["metric"] >= r["baseline"] - 0.02
        assert os.path.isdir(r["candidate"])
        fp = load_fingerprint(r["candidate"])
        assert fp is not None
        # the promoted model becomes the resident baseline
        assert loop.model is not None
        assert loop._cycle == 1

    def test_journal_resume_skips_completed_refit(self, tmp_path):
        """A crash between candidate save and swap resumes at the gate:
        the journaled candidate is reused, not refit."""
        loop = _loop_fixture(tmp_path)
        Xn, yn = _linear_data(500, seed=31, shift=2.0)
        loop.append(Xn, yn)

        class _Boom(Exception):
            pass

        class _CrashService:
            ladder = (8,)

            def reload(self, path):
                raise _Boom("killed mid-swap")

        loop.attach(_CrashService())
        with pytest.raises(_Boom):
            loop.run_cycle()
        # a fresh process over the same journal resumes the SAME cycle;
        # the drift window rehydrates from the store's appended segments
        # (no manual re-observe — the rows are on disk)
        loop2 = ContinualLoop(str(loop.store.path), loop.model_dir,
                              params=loop.params, seed=3)
        loop2.load_resident()
        assert loop2.monitor.window_rows == len(Xn)
        assert loop2._cycle == 0  # resumed IN the crashed cycle
        cand = loop2._pending_candidate()
        assert cand is not None and os.path.isdir(cand["model_dir"])
        r = loop2.run_cycle()  # no service attached: promote = adopt
        assert r["status"] == "promoted"
        assert r["candidate"] == cand["model_dir"]

    def test_rejected_candidate_never_swaps(self, tmp_path):
        """A refit that scores worse than the resident on the holdout
        is rejected before any serving interaction."""
        loop = _loop_fixture(tmp_path)
        # an impossible tolerance (candidate must BEAT the baseline by a
        # full accuracy point) forces the rejection branch determinately
        loop.params.metric_tolerance = -1.0
        Xn, yn = _linear_data(500, seed=32, shift=2.0)
        loop.append(Xn, yn)
        r = loop.run_cycle()
        assert r["status"] == "rejected"
        assert loop._cycle == 1  # cycle consumed, no promotion recorded

    def test_rejected_cycle_cools_down_until_new_rows(self, tmp_path):
        """After a rejection the supervisor must NOT re-run a full
        refit on identical data every poll — drift alone is not new
        evidence. New appends lift the cooldown."""
        loop = _loop_fixture(tmp_path)
        loop.params.metric_tolerance = -1.0   # force rejection
        Xn, yn = _linear_data(500, seed=33, shift=2.0)
        loop.append(Xn, yn)
        assert loop.run_cycle()["status"] == "rejected"
        # same data: no refit, just a cheap cooldown outcome
        assert loop.run_cycle()["status"] == "cooldown"
        assert loop.run_cycle()["status"] == "cooldown"
        # new rows arrive -> the gate is retried
        loop.params.metric_tolerance = 0.5
        loop.append(*_linear_data(200, seed=34, shift=2.0))
        assert loop.run_cycle()["status"] == "promoted"

    def test_promotion_survives_missing_fingerprint(self, tmp_path):
        """Fingerprint capture is best-effort: a promoted model without
        one must keep the previous drift baseline (fresh window), not
        raise after the swap landed and wedge the supervisor."""
        loop = _loop_fixture(tmp_path)
        old_fp = loop.monitor.fingerprint
        import transmogrifai_tpu.workflow.workflow as W
        orig = W.Workflow.__dict__["_capture_fingerprint"]  # staticmethod
        W.Workflow._capture_fingerprint = staticmethod(
            lambda *a, **k: None)
        try:
            loop.append(*_linear_data(500, seed=35, shift=2.0))
            r = loop.run_cycle()
        finally:
            W.Workflow._capture_fingerprint = orig
        assert r["status"] == "promoted"
        assert loop.monitor is not None
        assert loop.monitor.fingerprint is old_fp  # baseline kept
        assert loop.monitor.window_rows == 0       # window reset
        assert loop._cycle == 1                    # cycle completed

    def test_refit_max_rows_caps_the_training_range(self, tmp_path):
        """refit_max_rows bounds host materialization: the refit's
        dataset covers at most that many trailing rows."""
        loop = _loop_fixture(tmp_path)
        loop.params.refit_max_rows = 300
        seen = {}
        orig = ContinualLoop._dataset

        def spy(self, r0, r1):
            seen["range"] = (r0, r1)
            return orig(self, r0, r1)

        ContinualLoop._dataset = spy
        try:
            loop.append(*_linear_data(500, seed=36, shift=2.0))
            r = loop.run_cycle()
        finally:
            ContinualLoop._dataset = orig
        assert r["status"] in ("promoted", "rejected")
        r0, r1 = seen["range"]
        assert r1 - r0 == 300

    def test_restart_refit_reuses_fitted_feature_stages(self, tmp_path):
        """load_resident adopts the ARTIFACT's graph (original uids,
        fitted transformers): a restart refit reuses the vectorizer the
        serving model scores with — only the predictor is swapped for a
        fresh estimator."""
        loop = _loop_fixture(tmp_path)
        loop2 = ContinualLoop(str(loop.store.path), loop.model_dir,
                              params=loop.params, seed=4)
        loop2.load_resident()
        pred_f, label_f = loop2._result_features
        vec_f = next(p for p in pred_f.parents if not p.is_response)
        artifact_uids = {f.uid for rf in loop2.model.result_features
                         for f in rf.traverse()}
        assert vec_f.uid in artifact_uids  # reused, not rebuilt
        assert label_f.uid in artifact_uids
        assert loop2._estimator.uid not in loop2.model.fitted  # fresh
        Xn, yn = _linear_data(500, seed=37, shift=2.0)
        loop2.append(Xn, yn)
        r = loop2.run_cycle()
        assert r["status"] == "promoted", r

    def test_n_bins_param_threads_into_the_fingerprint(self, tmp_path):
        """ContinualParams.n_bins is not dead config: the loop's trains
        carry it into the captured fingerprint's histogram geometry."""
        loop = _loop_fixture(tmp_path, n_bins=7)
        assert loop.model.training_fingerprint.n_bins == 7
        assert loop.model.training_fingerprint.n_rows == 1200

    def test_gated_swap_auto_rollback_off_keeps_candidate_live(self):
        """auto_rollback=False is a policy choice, not a blind spot: the
        regressed candidate STAYS live (reported, not reverted), so the
        loop's resident state and the serving state agree."""
        from transmogrifai_tpu.continual import gated_swap

        class _Svc:
            ladder = (8,)

            def __init__(self):
                self.rolled = False

            def reload(self, path):
                return {"version": "v2"}

            def rollback(self):
                self.rolled = True
                return {"version": "v1"}

            def score(self, rows):
                raise RuntimeError("eval down")

        rows, y = [{"f0": 0.0}] * 4, np.zeros(4)
        svc = _Svc()
        r = gated_swap(svc, "unused", rows, y, baseline=0.9,
                       tolerance=0.02)
        assert r["status"] == "rolled_back" and svc.rolled
        svc2 = _Svc()
        r2 = gated_swap(svc2, "unused", rows, y, baseline=0.9,
                        tolerance=0.02, auto_rollback=False)
        assert r2["status"] == "promoted" and not svc2.rolled
        assert "regressed" in r2

    def test_gated_swap_unchanged_candidate_never_rolls_back(self):
        """A candidate content-identical to the live version (a warm
        refit that converged in zero steps) makes reload a no-op — the
        gate must NOT run the live eval and must NOT rollback() on its
        failure, or a version that was never displaced gets popped and
        serving silently reverts to the stale previous artifact."""
        from transmogrifai_tpu.continual import gated_swap

        class _Svc:
            ladder = (8,)

            def __init__(self):
                self.rolled = False
                self.scored = False

            def reload(self, path):
                return {"status": "unchanged", "version": "v2"}

            def rollback(self):
                self.rolled = True
                return {"version": "v1"}

            def score(self, rows):
                self.scored = True
                raise RuntimeError("eval down")

        svc = _Svc()
        r = gated_swap(svc, "unused", [{"f0": 0.0}] * 4, np.zeros(4),
                       baseline=0.9, tolerance=0.02)
        assert r["status"] == "promoted" and r.get("unchanged")
        assert not svc.rolled and not svc.scored

    def test_loop_auto_rollback_off_installs_the_live_candidate(
            self, tmp_path):
        loop = _loop_fixture(tmp_path, auto_rollback=False)

        class _Svc:
            ladder = (8,)
            rolled = False

            def reload(self, path):
                return {"version": "v2"}

            def rollback(self):
                self.rolled = True
                return {"version": "v1"}

            def score(self, rows):
                raise RuntimeError("eval down")

        svc = _Svc()
        loop.attach(svc)
        Xn, yn = _linear_data(500, seed=35, shift=2.0)
        loop.append(Xn, yn)
        r = loop.run_cycle()
        assert r["status"] == "promoted", r
        assert not svc.rolled  # policy honored: no hidden rollback
        assert loop._cycle == 1

    def test_refit_max_iter_scoped_to_the_warm_fit(self, tmp_path):
        """refit_max_iter caps the WARM optimizer budget only — a later
        cold fit of the same estimator sees its own budget again."""
        loop = _loop_fixture(tmp_path, refit_max_iter=5)
        cold_budget = loop._estimator.max_iter
        Xn, yn = _linear_data(500, seed=36, shift=2.0)
        loop.append(Xn, yn)
        assert loop.run_cycle()["status"] == "promoted"
        assert loop._estimator.max_iter == cold_budget
        assert loop._estimator.init_params is None

    def test_holdout_eval_regressor_with_integral_labels(self):
        """classification is judged from the model's OUTPUT (probability
        head), not label integrality — an integer-valued regression
        target must keep BOTH gates on (negative-)MSE, or the live gate
        compares accuracy against -MSE and never fires."""
        from transmogrifai_tpu.continual import holdout_eval
        from transmogrifai_tpu.models.linear import OpLinearRegression
        rng = np.random.default_rng(44)
        X = rng.standard_normal((300, D))
        beta = np.random.default_rng(99).normal(size=D)
        y = np.round(X @ beta * 3.0)  # integral regression target
        cols = {f"f{j}": X[:, j] for j in range(D)}
        cols["label"] = y
        schema = {f"f{j}": t.Real for j in range(D)}
        schema["label"] = t.Real
        ds = Dataset(cols, schema)
        preds, label = FeatureBuilder.from_dataset(
            ds, response="label", response_type=t.RealNN)
        vec = RealVectorizer(track_nulls=False).set_input(*preds) \
            .get_output()
        pred = OpLinearRegression().set_input(label, vec).get_output()
        model = Workflow().set_result_features(pred, label) \
            .set_input_dataset(ds).train()
        metric, classification = holdout_eval(model, ds, y)
        assert classification is False
        assert metric <= 0.0  # negative MSE, not an accuracy in [0, 1]

    def test_loop_counters_and_staleness_recorded(self, tmp_path):
        from transmogrifai_tpu.obs.metrics import get_registry
        loop = _loop_fixture(tmp_path)
        Xn, yn = _linear_data(500, seed=33, shift=2.0)
        before = {
            k: _counter_value(get_registry(), k)
            for k in ("continual_promotions_total",
                      "continual_drift_detected_total")}
        loop.append(Xn, yn)
        r = loop.run_cycle()
        assert r["status"] == "promoted"
        assert r["staleness_s"] > 0.0
        reg = get_registry()
        assert _counter_value(reg, "continual_promotions_total") == \
            before["continual_promotions_total"] + 1
        assert _counter_value(reg, "continual_drift_detected_total") == \
            before["continual_drift_detected_total"] + 1

    def test_goodput_report_accounts_cycles(self, tmp_path):
        from transmogrifai_tpu.obs.goodput import build_report
        from transmogrifai_tpu.obs.trace import TRACER
        with TRACER.span("run:test-continual", category="run",
                         new_trace=True) as root:
            loop = _loop_fixture(tmp_path)
            Xn, yn = _linear_data(500, seed=34, shift=2.0)
            loop.append(Xn, yn)
            assert loop.run_cycle()["status"] == "promoted"
            rep = build_report(root, TRACER.trace_spans(root.trace_id))
        cont = rep.to_json()["continual"]
        assert cont["cycles"] == 1 and cont["promoted"] == 1
        assert cont["drift_detected"] == 1
        assert cont["last_staleness_s"] > 0.0


def _counter_value(reg, name) -> float:
    fam = reg.to_json().get(name)
    if not fam or not fam.get("series"):
        return 0.0
    return float(sum(s.get("value", 0.0) for s in fam["series"]))
