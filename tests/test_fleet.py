"""serving/fleet.py + serving/router.py: multi-model tenancy — scoring
signatures, shared bucket programs (RetraceMonitor-asserted dedup with
bit-identical numerics), per-tenant token-bucket quotas + priority
shedding, warmup manifests / persistent-compile cold-start accounting,
the fleet HTTP frontend, rolling swaps under traffic, and the goodput
fleet section."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.analysis.retrace import MONITOR
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models import (
    OpLogisticRegression, OpRandomForestClassifier)
from transmogrifai_tpu.ops.numeric import RealVectorizer
from transmogrifai_tpu.serving import ScoreError
from transmogrifai_tpu.serving.fleet import (
    FleetConfig, FleetService, scoring_signature)
from transmogrifai_tpu.serving.router import Router, TenantPolicy, TokenBucket
from transmogrifai_tpu.serving.service import ScoringService, ServingConfig
from transmogrifai_tpu.workflow import Workflow
from transmogrifai_tpu.workflow.serialization import (
    load_model, load_warmup_manifest, save_warmup_manifest)

ROWS = [{"x1": 0.3, "x2": -1.2}, {"x1": -0.5, "x2": 0.8},
        {"x1": 2.0, "x2": 0.1}]


def _train(y_sign=1.0, forest=True, depth=2, n=120, max_iter=30):
    """Forest pipelines over IDENTICAL features (seed pinned) so only
    the LABELS — and therefore only the fitted tree values — differ
    between same-shaped models."""
    rng = np.random.default_rng(7)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    lrng = np.random.default_rng(int(abs(y_sign * 10)) + (3 if forest
                                                          else 5))
    y = ((x1 + y_sign * 0.5 * x2 + lrng.normal(0, 0.3, n)) > 0) \
        .astype(np.float64)
    ds = Dataset({"x1": x1, "x2": x2, "y": y},
                 {"x1": t.Real, "x2": t.Real, "y": t.Integral})
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = RealVectorizer(track_nulls=False).set_input(*preds).get_output()
    est = (OpRandomForestClassifier(n_trees=3, max_depth=depth)
           if forest else OpLogisticRegression(max_iter=max_iter))
    pred = est.set_input(label, vec).get_output()
    return Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train()


@pytest.fixture(scope="module")
def model_dirs(tmp_path_factory):
    """m1/m2: same-shaped forests (different fitted trees); m3: a
    deeper forest (different tree-table shapes); plus an m1-shaped swap
    candidate."""
    base = tmp_path_factory.mktemp("fleet-models")
    dirs = {}
    for name, kw in (("m1", dict(y_sign=1.0)),
                     ("m2", dict(y_sign=-1.0)),
                     ("m1_v2", dict(y_sign=-1.0)),
                     ("m3", dict(y_sign=1.0, depth=4))):
        _train(**kw).save(str(base / name))
        dirs[name] = str(base / name)
    return dirs


def _fleet_config(model_dirs, **kw):
    cfg = dict(serving={"max_batch": 4, "batch_wait_ms": 1.0})
    cfg.update(kw)
    return FleetConfig(models={k: model_dirs[k]
                               for k in kw.pop("names", ())}, **cfg)


# --------------------------------------------------------------------- #
# scoring signature                                                     #
# --------------------------------------------------------------------- #

def test_scoring_signature_groups_tree_models(model_dirs):
    m1 = load_model(model_dirs["m1"])
    m2 = load_model(model_dirs["m2"])
    m3 = load_model(model_dirs["m3"])
    # same pipeline, different fitted TREE VALUES: tree tables flow as
    # device_constants jit arguments, so only their shapes key the
    # signature — m1 and m2 share
    assert scoring_signature(m1) == scoring_signature(m2)
    # deeper trees = different table shapes = different programs
    assert scoring_signature(m1) != scoring_signature(m3)
    # deterministic across loads of one artifact
    assert scoring_signature(m1) == scoring_signature(
        load_model(model_dirs["m1"]))


def test_scoring_signature_groups_lifted_linear_tenants():
    """PR 13 parameter lifting: linear-family weights flow as traced
    jit arguments (`LogisticRegressionModel.device_constants`), so two
    different same-shaped LR fits SHARE one compiled program — the
    zero-trace-onboarding contract for K-replica and warm-refit
    tenants."""
    a = _train(y_sign=1.0, forest=False)
    b = _train(y_sign=-1.0, forest=False)
    assert scoring_signature(a) == scoring_signature(b)
    assert scoring_signature(a) == scoring_signature(a)


def test_scoring_signature_is_value_sensitive_for_closure_constants():
    """Honesty check for state that still BAKES into the trace: a
    different max_iter changes nothing traced (both fits share), but
    hyperparams steering static control flow — a GBT learning rate, a
    GLM link — are value-digested via `signature_params`, and the
    quantization mode is folded into the key so a quantized and an f32
    build of ONE model can never adopt each other's programs."""
    a = _train(y_sign=1.0, forest=False)
    assert scoring_signature(a) != scoring_signature(a, quant="int8")
    assert scoring_signature(a, quant="int8") == \
        scoring_signature(a, quant="int8")
    assert scoring_signature(a, quant="int8") != \
        scoring_signature(a, quant="int4")


# --------------------------------------------------------------------- #
# shared bucket programs (the tentpole dedup contract)                  #
# --------------------------------------------------------------------- #

def test_shared_program_dedup_retrace_asserted(model_dirs):
    """Satellite acceptance: loading a second same-shaped model into a
    FleetService compiles ZERO new bucket programs
    (RetraceMonitor-asserted); a differently-shaped third compiles its
    own ladder."""
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"]},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    try:
        before = MONITOR.snapshot()
        fleet.add_model("m2", model_dirs["m2"])
        assert MONITOR.delta(before) == {}  # zero new traces, anywhere
        m2_info = fleet.models()["m2"]
        assert all(v == 0 for v in
                   m2_info["versions"][-1]["compile_counts"].values())
        assert m2_info["shared_from"] is not None

        before = MONITOR.snapshot()
        fleet.add_model("m3", model_dirs["m3"])
        assert sum(MONITOR.delta(before).values()) > 0
        assert fleet.models()["m3"]["shared_from"] is None

        report = fleet.pool.report()
        assert len(report) == 2
        sizes = sorted(len(e["members"]) for e in report.values())
        assert sizes == [1, 2]
    finally:
        fleet.stop()


def test_adopted_model_scores_bit_identical(model_dirs):
    """The adopted model executes the REFERENCE model's compiled
    program with its OWN tree tables as arguments — outputs must be
    bit-identical to an unshared solo load."""
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"], "m2": model_dirs["m2"]},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    fleet.start()
    try:
        res = fleet.score("m2", ROWS)
        solo = load_model(model_dirs["m2"])
        ds = Dataset.from_rows(ROWS, schema={"x1": t.Real, "x2": t.Real})
        direct = solo.score_compiled(ds)
        (name,) = [k for k in direct
                   if isinstance(direct[k], dict)
                   and "prediction" in direct[k]]
        np.testing.assert_array_equal(
            np.asarray(res.outputs[name]["prediction"]),
            np.asarray(direct[name]["prediction"]))
        np.testing.assert_array_equal(
            np.asarray(res.outputs[name]["probability"]),
            np.asarray(direct[name]["probability"]))
        # and the two members genuinely answer differently (different
        # fitted trees through one program); result names embed uids,
        # so resolve each model's own prediction key
        r1 = fleet.score("m1", ROWS)
        (name1,) = [k for k in r1.outputs
                    if isinstance(r1.outputs[k], dict)
                    and "prediction" in r1.outputs[k]]
        assert not np.array_equal(
            np.asarray(r1.outputs[name1]["probability"]),
            np.asarray(res.outputs[name]["probability"]))
    finally:
        fleet.stop()


def test_same_shaped_hot_swap_warms_with_zero_traces(model_dirs):
    """A rolling swap to a same-shaped candidate adopts the resident
    programs: the whole reload performs zero new traces."""
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"]},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    fleet.start()
    try:
        before = MONITOR.snapshot()
        out = fleet.reload_model("m1", model_dirs["m1_v2"])
        assert out["status"] == "swapped"
        assert MONITOR.delta(before) == {}
        # rollback stays instant/warm as ever
        back = fleet.rollback_model("m1")
        assert back["status"] == "rolled_back"
    finally:
        fleet.stop()


# --------------------------------------------------------------------- #
# router: quotas + priorities                                           #
# --------------------------------------------------------------------- #

def test_token_bucket_rate_and_burst():
    b = TokenBucket(rate=100.0, burst=10.0)
    assert b.try_take(10)          # full burst available
    assert not b.try_take(5)       # drained
    time.sleep(0.06)               # ~6 tokens refill at 100/s
    assert b.try_take(4)
    assert TokenBucket(rate=float("inf"), burst=1.0).try_take(10 ** 9)


def test_router_quota_sheds_only_the_offender():
    r = Router(tenants={"gold": TenantPolicy(rate=1e9, priority=1),
                        "trial": TenantPolicy(rate=10, burst=10,
                                              priority=0)})
    for _ in range(40):
        assert r.admit("gold", 8, queue_frac=0.0) == "gold"
    with pytest.raises(ScoreError) as ei:
        for _ in range(10):  # 10-row burst drains after one request
            r.admit("trial", 8, queue_frac=0.0)
    assert ei.value.code == "quota_exceeded"
    # gold keeps flowing after trial is shed
    assert r.admit("gold", 8, queue_frac=0.0) == "gold"
    snap = r.snapshot()
    assert snap["trial"]["shed"] >= 1
    assert snap["gold"]["shed"] == 0


def test_router_priority_shedding_is_graded():
    r = Router(tenants={"low": TenantPolicy(priority=0),
                        "mid": TenantPolicy(priority=1),
                        "high": TenantPolicy(priority=2)},
               shed_watermark=0.5)
    # below the watermark everyone is admitted
    for name in ("low", "mid", "high"):
        r.admit(name, 1, queue_frac=0.4)
    # just past the watermark: only the lowest class sheds
    with pytest.raises(ScoreError) as ei:
        r.admit("low", 1, queue_frac=0.55)
    assert ei.value.code == "shed_low_priority"
    r.admit("mid", 1, queue_frac=0.55)
    r.admit("high", 1, queue_frac=0.55)
    # near capacity: everything below the TOP class sheds...
    with pytest.raises(ScoreError):
        r.admit("mid", 1, queue_frac=0.99)
    # ...but the top class is never priority-shed (the bounded queue's
    # own queue_full backstop handles true saturation)
    r.admit("high", 1, queue_frac=0.99)


def test_router_unknown_tenant_gets_default_policy():
    r = Router(tenants={"gold": TenantPolicy(rate=1e9, priority=2)})
    # anonymous traffic is admitted unmetered but at the LOWEST
    # configured priority: it sheds first under pressure
    assert r.admit(None, 5, queue_frac=0.0) == "default"
    with pytest.raises(ScoreError):
        r.admit("anon", 1, queue_frac=0.9)
    r.admit("gold", 1, queue_frac=0.9)
    # explicit default policy is honored
    r2 = Router(tenants={"gold": TenantPolicy(priority=1)},
                default=TenantPolicy(rate=5, burst=5, priority=0))
    with pytest.raises(ScoreError) as ei:
        for _ in range(5):
            r2.admit("anon", 4, queue_frac=0.0)
    assert ei.value.code == "quota_exceeded"


def test_router_caps_wire_supplied_tenant_cardinality():
    """Unknown tenant names come off the wire: past `max_tenants` they
    fold into the shared default bucket instead of minting unbounded
    per-tenant state + labeled metric series."""
    r = Router(tenants={"gold": TenantPolicy(priority=1)}, max_tenants=3)
    assert r.admit("scan-1", 1, 0.0) == "scan-1"
    assert r.admit("scan-2", 1, 0.0) == "scan-2"
    for i in range(3, 50):  # cap reached: all fold into "default"
        assert r.admit(f"scan-{i}", 1, 0.0) == "default"
    snap = r.snapshot()
    assert len(snap) <= 4 + 1  # gold + 2 scans + default (+1 slack)
    assert "scan-49" not in snap


def test_router_snapshot_delta():
    r = Router(tenants={"a": TenantPolicy(), "b": TenantPolicy()})
    r.admit("a", 2, 0.0)
    r.note_success("a", "m", 2, 0.01)
    before = r.snapshot()
    r.note_success("b", "m", 7, 0.01)
    delta = r.delta(before)
    assert delta == {"b": {"requests": 1, "rows": 7, "shed": 0,
                           "errors": 0}}


# --------------------------------------------------------------------- #
# fleet service surface                                                 #
# --------------------------------------------------------------------- #

def test_fleet_unknown_model_and_duplicate_name(model_dirs):
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"]},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    fleet.start()
    try:
        with pytest.raises(ScoreError) as ei:
            fleet.score("nope", ROWS)
        assert ei.value.code == "not_found"
        with pytest.raises(ScoreError) as ei:
            fleet.add_model("m1", model_dirs["m2"])
        assert ei.value.code == "bad_request"
        with pytest.raises(ScoreError) as ei:
            fleet.remove_model("nope")
        assert ei.value.code == "not_found"
    finally:
        fleet.stop()


def test_fleet_config_validates_serving_keys(model_dirs):
    with pytest.raises(ValueError, match="unknown serving config"):
        FleetService(FleetConfig(models={"m1": model_dirs["m1"]},
                                 serving={"max_batchs": 8}))
    with pytest.raises(ValueError, match="model spec"):
        FleetService(FleetConfig(models={"m1": {"dir": "x"}}))


def test_fleet_rolling_swap_zero_drops_for_other_models(model_dirs):
    """In-process version of the smoke assertion: traffic on m2/m3
    sees zero errors while m1 is swapped."""
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"], "m2": model_dirs["m2"],
                "m3": model_dirs["m3"]},
        serving={"max_batch": 4, "batch_wait_ms": 1.0,
                 "max_queue": 512}))
    fleet.start()
    errors = {"m2": 0, "m3": 0}
    served = {"m2": 0, "m3": 0}
    halt = threading.Event()

    def client(model):
        while not halt.is_set():
            try:
                fleet.score(model, ROWS, deadline_ms=10_000)
                served[model] += 1
            except Exception:
                errors[model] += 1

    threads = [threading.Thread(target=client, args=(m,))
               for m in ("m2", "m3")]
    try:
        for th in threads:
            th.start()
        out = fleet.reload_model("m1", model_dirs["m1_v2"])
        time.sleep(0.2)
    finally:
        halt.set()
        for th in threads:
            th.join(timeout=5)
        fleet.stop()
    assert out["status"] == "swapped"
    assert errors == {"m2": 0, "m3": 0}
    assert served["m2"] > 0 and served["m3"] > 0


def test_fleet_goodput_section_from_rolling_swap(model_dirs):
    from transmogrifai_tpu.obs.goodput import build_report
    from transmogrifai_tpu.obs.trace import TRACER
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"]},
        tenants={"acme": {"rate": 1e9, "priority": 1}},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    fleet.start()
    try:
        with TRACER.span("run:test-fleet", category="run",
                         new_trace=True) as root:
            fleet.score("m1", ROWS, tenant="acme")
            fleet.reload_model("m1", model_dirs["m1_v2"])
            fleet.score("m1", ROWS, tenant="acme")
        report = build_report(root, TRACER.trace_spans(root.trace_id))
    finally:
        fleet.stop()
    assert report.fleet["swaps"] == 1
    assert report.fleet["swapped"] == 1
    assert report.fleet["swap_wall_s"] > 0
    assert report.to_json()["fleet"]["swaps"] == 1


# --------------------------------------------------------------------- #
# warmup manifest + persistent-compile accounting                       #
# --------------------------------------------------------------------- #

def test_warmup_manifest_roundtrip(tmp_path):
    d = str(tmp_path)
    assert load_warmup_manifest(d) is None
    assert save_warmup_manifest(d, {"fingerprint": "abc", "warm_s": 1.5})
    m = load_warmup_manifest(d)
    assert m["fingerprint"] == "abc" and m["warm_s"] == 1.5
    # garbage/foreign-version sidecars read as cold, never raise
    (tmp_path / "warmup.json").write_text("{torn")
    assert load_warmup_manifest(d) is None
    (tmp_path / "warmup.json").write_text(
        json.dumps({"warmup_version": 99, "fingerprint": "abc"}))
    assert load_warmup_manifest(d) is None


def test_cold_warmup_writes_manifest_and_warm_start_claims_savings(
        model_dirs, monkeypatch, tmp_path):
    """First service over an artifact records its cold warmup in the
    sidecar; a second service with the persistent compile cache enabled
    matches the manifest and records `serving_compile_cache_saved_s`."""
    import transmogrifai_tpu.utils.compile_cache as cc
    # pretend-enable the cache: touching the real process-global jax
    # compilation-cache config from a unit test would leak into every
    # later compile in the suite
    monkeypatch.setattr(cc, "enable_compile_cache",
                        lambda path=None, min_compile_s=0.5:
                        str(tmp_path / "cache"))
    svc = ScoringService.from_path(
        model_dirs["m3"], config=ServingConfig(max_batch=4))
    svc.stop()
    manifest = load_warmup_manifest(model_dirs["m3"])
    assert manifest is not None
    assert manifest["warm_s"] > 0 and manifest["compiles"] > 0
    assert manifest["ladder"] == [1, 2, 4]

    svc2 = ScoringService.from_path(
        model_dirs["m3"],
        config=ServingConfig(max_batch=4, compile_cache=True))
    svc2.stop()
    info = svc2.health()["versions"][-1]
    assert "compile_cache_saved_s" in info
    assert "serving_compile_cache_saved_s" in svc2.registry.to_json()


def test_manifest_ladder_mismatch_reads_as_cold(model_dirs, monkeypatch,
                                                tmp_path):
    import transmogrifai_tpu.utils.compile_cache as cc
    monkeypatch.setattr(cc, "enable_compile_cache",
                        lambda path=None, min_compile_s=0.5:
                        str(tmp_path / "cache"))
    save_warmup_manifest(model_dirs["m3"], {
        "fingerprint": "not-the-fingerprint", "ladder": [1, 2, 4],
        "warm_s": 99.0, "compiles": 3})
    svc = ScoringService.from_path(
        model_dirs["m3"],
        config=ServingConfig(max_batch=4, compile_cache=True))
    svc.stop()
    info = svc.health()["versions"][-1]
    # mismatched fingerprint: no savings claim; the genuine cold warmup
    # does NOT overwrite someone else's sidecar blindly either — it
    # writes its own record (fingerprint now current)
    assert "compile_cache_saved_s" not in info
    m = load_warmup_manifest(model_dirs["m3"])
    assert m["fingerprint"] == info["version"]


def test_adoption_warmed_member_claims_no_compile_cache_savings(
        model_dirs, monkeypatch, tmp_path):
    """A member warmed through SHARED programs (zero traces) must not
    book the manifest's cold baseline as compile-cache savings — that
    recovery belongs to program sharing, not the persistent cache."""
    import transmogrifai_tpu.utils.compile_cache as cc
    monkeypatch.setattr(cc, "enable_compile_cache",
                        lambda path=None, min_compile_s=0.5:
                        str(tmp_path / "cache"))
    # give m2 a plausible manifest matching its fingerprint + ladder
    from transmogrifai_tpu.workflow.serialization import model_fingerprint
    save_warmup_manifest(model_dirs["m2"], {
        "fingerprint": model_fingerprint(model_dirs["m2"]),
        "ladder": [1, 2, 4], "warm_s": 9.9, "compiles": 3})
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"], "m2": model_dirs["m2"]},
        serving={"max_batch": 4, "batch_wait_ms": 1.0},
        compile_cache=True))
    try:
        info = fleet.models()["m2"]["versions"][-1]
        assert sum(int(v) for v in info["compile_counts"].values()) == 0
        assert "compile_cache_saved_s" not in info
    finally:
        fleet.stop()


def test_add_model_reservation_blocks_duplicates_and_lookups(model_dirs):
    """The name is reserved under the lock before the slow load/warm: a
    concurrent duplicate add fails fast and scoring against the
    still-loading name is a structured not_found, never a half-built
    member."""
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"]},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    try:
        with fleet._lock:
            fleet._services["loading"] = None  # in-flight reservation
        with pytest.raises(ScoreError) as ei:
            fleet.add_model("loading", model_dirs["m2"])
        assert ei.value.code == "bad_request"
        with pytest.raises(ScoreError) as ei:
            fleet.score("loading", ROWS)
        assert ei.value.code == "not_found"
        assert "loading" not in fleet.models()
        # a failed load releases its reservation
        with pytest.raises(Exception):
            fleet.add_model("bad", "/nonexistent/model/dir")
        fleet.add_model("bad", model_dirs["m2"])  # name reusable
    finally:
        fleet.stop()


def test_shared_warmup_never_becomes_cold_baseline(model_dirs):
    """An adoption-warmed member (zero compiles) must not write a
    near-zero 'cold' manifest that would poison future savings math."""
    import os
    wpath = os.path.join(model_dirs["m2"], "warmup.json")
    if os.path.exists(wpath):
        os.unlink(wpath)
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"], "m2": model_dirs["m2"]},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    fleet.stop()
    m1_manifest = load_warmup_manifest(model_dirs["m1"])
    assert m1_manifest is not None and m1_manifest["compiles"] > 0
    assert load_warmup_manifest(model_dirs["m2"]) is None


# --------------------------------------------------------------------- #
# HTTP frontend                                                         #
# --------------------------------------------------------------------- #

@pytest.fixture()
def fleet_http(model_dirs):
    from transmogrifai_tpu.serving.http import serve_fleet
    fleet = FleetService(FleetConfig(
        models={"m1": model_dirs["m1"], "m3": model_dirs["m3"]},
        tenants={"gold": {"rate": 1e9, "priority": 1},
                 "trial": {"rate": 3, "burst": 3, "priority": 0}},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    fleet.start()
    server, _ = serve_fleet(fleet, port=0, block=False)
    yield fleet, f"http://127.0.0.1:{server.port}"
    server.shutdown()
    server.server_close()
    fleet.stop()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_fleet_http_score_models_health_metrics(fleet_http):
    fleet, base = fleet_http
    out = _post(f"{base}/score", {"model": "m1", "rows": ROWS},
                headers={"X-Tenant": "gold"})
    assert out["model"] == "m1" and len(out["scores"]) == 3
    health = json.loads(urllib.request.urlopen(
        f"{base}/healthz", timeout=30).read())
    assert health["status"] == "ok"
    assert set(health["models"]) == {"m1", "m3"}
    assert health["tenants"]["gold"]["requests"] >= 1
    models = json.loads(urllib.request.urlopen(
        f"{base}/models", timeout=30).read())["models"]
    assert set(models) == {"m1", "m3"}
    prom = urllib.request.urlopen(f"{base}/metrics", timeout=30) \
        .read().decode()
    assert 'fleet_requests_total{model="m1",tenant="gold"}' in prom
    mjson = json.loads(urllib.request.urlopen(
        f"{base}/metrics?format=json", timeout=30).read())
    assert "fleet" in mjson and set(mjson["models"]) == {"m1", "m3"}


def test_fleet_http_error_mapping(fleet_http):
    fleet, base = fleet_http
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{base}/score", {"model": "nope", "rows": ROWS})
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{base}/score", {"rows": ROWS})
    assert ei.value.code == 400
    # over-quota tenant -> 429 with the structured code; gold untouched
    codes = []
    for _ in range(4):
        try:
            _post(f"{base}/score", {"model": "m1", "rows": ROWS,
                                    "tenant": "trial"})
            codes.append(200)
        except urllib.error.HTTPError as e:
            codes.append(e.code)
            body = json.loads(e.read())
            assert body["error"] == "quota_exceeded"
    assert 429 in codes
    _post(f"{base}/score", {"model": "m1", "rows": ROWS,
                            "tenant": "gold"})


def test_fleet_http_reload_and_rollback(fleet_http, model_dirs):
    fleet, base = fleet_http
    out = _post(f"{base}/reload", {"model": "m1",
                                   "model_location": model_dirs["m1_v2"]})
    assert out["status"] == "swapped"
    out = _post(f"{base}/reload", {"model": "m1", "rollback": True})
    assert out["status"] == "rolled_back"
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{base}/reload", {"model": "nope",
                                 "model_location": model_dirs["m2"]})
    assert ei.value.code == 404


# --------------------------------------------------------------------- #
# params / CLI threading                                                #
# --------------------------------------------------------------------- #

def test_serving_params_fleet_and_compile_cache_roundtrip():
    from transmogrifai_tpu.workflow.params import ServingParams
    sp = ServingParams.from_json({
        "max_batch": 16, "compile_cache": True,
        "compile_cache_dir": "/tmp/x", "warmup_manifest": False,
        "fleet": {"models": {"a": "dir_a"},
                  "tenants": {"t": {"rate": 5, "priority": 1}}}})
    assert sp.to_json()["compile_cache"] is True
    cfg = sp.to_config()
    assert cfg.compile_cache is True
    assert cfg.compile_cache_dir == "/tmp/x"
    assert cfg.warmup_manifest is False
    fc = sp.to_fleet_config()
    assert isinstance(fc, FleetConfig)
    assert fc.models == {"a": "dir_a"}
    assert fc.compile_cache is True
    # service-level knobs become the members' shared serving defaults
    assert fc.serving["max_batch"] == 16
    assert fc.serving["warmup_manifest"] is False
    with pytest.raises(ValueError):
        ServingParams().to_fleet_config()
