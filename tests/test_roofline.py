"""Roofline scoring (PR 13): whole-pipeline fusion (one dispatch per
score call, trimmed program outputs), quantized int8/int4 inference
with stated wire tolerance, parameter-lifted linear tenants sharing one
compiled program, quant-aware scoring signatures, and the goodput
`scoring` section."""

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.analysis.retrace import DISPATCHES, MONITOR
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models import (
    OpGeneralizedLinearRegression, OpLinearRegression,
    OpLogisticRegression, OpRandomForestClassifier, OpXGBoostClassifier)
from transmogrifai_tpu.ops.numeric import RealVectorizer
from transmogrifai_tpu.serving.fleet import (
    FleetConfig, FleetService, scoring_signature)
from transmogrifai_tpu.serving.service import ScoringService, ServingConfig
from transmogrifai_tpu.workflow import Workflow
from transmogrifai_tpu.workflow.compiled import (
    CompiledScorer, ScoringQuant, dequantize_leaf, quantize_leaf)

N = 200


def _data(seed=7, n=N, y_from=lambda x1, x2, eps: x1 + 0.5 * x2 + eps):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = 3.0 * rng.normal(size=n) - 1.0
    y = (y_from(x1, x2, rng.normal(0, 0.3, n)) > 0).astype(np.float64)
    return Dataset({"x1": x1, "x2": x2, "y": y},
                   {"x1": t.Real, "x2": t.Real, "y": t.Integral})


def _train(est, seed=7, **data_kw):
    ds = _data(seed=seed, **data_kw)
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = RealVectorizer(track_nulls=False).set_input(*preds).get_output()
    pred = est.set_input(label, vec).get_output()
    model = Workflow().set_result_features(pred, label) \
        .set_input_dataset(ds).train()
    return model, pred, ds


def _score_ds(n=16, seed=3):
    rng = np.random.default_rng(seed)
    return Dataset({"x1": rng.normal(size=n),
                    "x2": 3.0 * rng.normal(size=n) - 1.0},
                   {"x1": t.Real, "x2": t.Real})


# --------------------------------------------------------------------- #
# whole-pipeline fusion                                                 #
# --------------------------------------------------------------------- #

def test_fused_plan_single_dispatch_per_score_call():
    model, pf, _ = _train(OpLogisticRegression(max_iter=20))
    scorer = model._ensure_compiled()
    assert scorer.fusable
    sub = _score_ds(5)
    scorer.score_padded(sub, 16)  # warm (compile)
    before = DISPATCHES.snapshot()
    scorer.score_padded(sub, 16)
    delta = DISPATCHES.delta(before)
    assert sum(delta.values()) == 1, delta
    # and a second bucket is again exactly one dispatch
    scorer.score_padded(sub, 8)
    before = DISPATCHES.snapshot()
    scorer.score_padded(sub, 8)
    assert sum(DISPATCHES.delta(before).values()) == 1


def test_fused_path_matches_general_segmented_path_exactly():
    model, pf, _ = _train(OpLogisticRegression(max_iter=20))
    scorer = model._ensure_compiled()
    sub = _score_ds(8)
    fused = scorer.score_fused(sub)
    general = scorer(sub)
    np.testing.assert_array_equal(
        np.asarray(fused[pf.name]["probability"]),
        np.asarray(general[pf.name]["probability"]))
    np.testing.assert_array_equal(
        np.asarray(fused[pf.name]["prediction"]),
        np.asarray(general[pf.name]["prediction"]))


def test_segment_outputs_trimmed_to_needed_uids():
    """The fused program returns ONLY result features — intermediates
    (vectorizer outputs) stay XLA-internal instead of becoming forced
    HBM materializations."""
    model, pf, _ = _train(OpLogisticRegression(max_iter=20))
    scorer = model._ensure_compiled()
    (out_uids,) = [u for i, u in enumerate(scorer._seg_out_uids)
                   if scorer.segments[i][0] == "device"]
    assert out_uids == [pf.uid]


@pytest.mark.parametrize("quant", [None, "int8"])
def test_score_padded_pad_region_invariance(quant):
    """Valid-row results are invariant to the bucket a batch was padded
    to — pad rows repeat a REAL row, so they never widen the quantized
    wire's per-batch range either."""
    model, pf, _ = _train(OpLogisticRegression(max_iter=20))
    scorer = model._ensure_compiled(quant=quant)
    sub = _score_ds(6)
    a = scorer.score_padded(sub, 8)
    b = scorer.score_padded(sub, 32)
    np.testing.assert_array_equal(
        np.asarray(a[pf.name]["probability"]),
        np.asarray(b[pf.name]["probability"]))


# --------------------------------------------------------------------- #
# quantized wire primitives                                             #
# --------------------------------------------------------------------- #

def test_quantize_leaf_roundtrip_within_stated_tolerance():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 7)).astype(np.float32) * 10.0
    for bits in (8, 4):
        wire = quantize_leaf(x, bits)
        back = np.asarray(dequantize_leaf(
            {k: np.asarray(v) for k, v in wire.items()}, bits))
        tol = wire["scale"] / 2.0 + 1e-6
        assert back.shape == x.shape
        assert np.all(np.abs(back - x) <= tol[None, :]), bits


def test_quantize_leaf_inf_never_corrupts_finite_batchmates():
    """A ±inf value must clip to the FINITE range bounds — it must not
    degenerate the affine fit and drag every finite batchmate's value
    off by orders of magnitude (post-review regression)."""
    x = np.array([[1000.0], [2000.0], [np.inf]], np.float32)
    wire = quantize_leaf(x, 8)
    back = np.asarray(dequantize_leaf(
        {k: np.asarray(v) for k, v in wire.items()}, 8))
    tol = float(wire["scale"][0]) / 2.0 + 1e-3
    assert abs(back[0, 0] - 1000.0) <= tol
    assert abs(back[1, 0] - 2000.0) <= tol
    assert back[2, 0] == pytest.approx(2000.0, abs=tol)  # clips to hi
    neg = np.array([-5.0, -3.0, -np.inf], np.float32)
    nwire = quantize_leaf(neg, 8)
    nback = np.asarray(dequantize_leaf(
        {k: np.asarray(v) for k, v in nwire.items()}, 8))
    ntol = float(nwire["scale"][0]) / 2.0 + 1e-4
    assert abs(nback[0] + 5.0) <= ntol
    assert abs(nback[1] + 3.0) <= ntol
    assert nback[2] == pytest.approx(-5.0, abs=ntol)  # clips to lo


def test_quantize_leaf_one_d_nan_and_constant_columns():
    x = np.array([1.0, np.nan, 3.0, 1.0], np.float32)
    wire = quantize_leaf(x, 8)
    assert "q1" in wire  # rank marker
    back = np.asarray(dequantize_leaf(
        {k: np.asarray(v) for k, v in wire.items()}, 8))
    assert back.shape == x.shape
    # NaN maps to lo — never an undefined uint8 cast
    assert back[1] == pytest.approx(1.0, abs=1e-6)
    # constant column round-trips exactly (scale degenerates to 1)
    const = quantize_leaf(np.full(8, 2.5, np.float32), 8)
    cback = np.asarray(dequantize_leaf(
        {k: np.asarray(v) for k, v in const.items()}, 8))
    np.testing.assert_allclose(cback, 2.5, atol=1e-6)


def test_scoring_quant_validation():
    assert ScoringQuant("int4").bits == 4
    assert ScoringQuant.resolve(None) is None
    assert ScoringQuant.resolve("int8") == ScoringQuant("int8")
    with pytest.raises(ValueError):
        ScoringQuant("fp8")


# --------------------------------------------------------------------- #
# quantized-vs-f32 parity per family                                    #
# --------------------------------------------------------------------- #

def _parity(est, prob_tol=None, agree_min=0.97, seed=7):
    model, pf, _ = _train(est, seed=seed)
    sub = _score_ds(64)
    f32 = model._ensure_compiled().score_padded(sub, 64)
    q = model._ensure_compiled(quant="int8").score_padded(sub, 64)
    pa = np.asarray(f32[pf.name]["prediction"])
    pb = np.asarray(q[pf.name]["prediction"])
    assert (pa == pb).mean() >= agree_min
    ra = np.asarray(f32[pf.name]["rawPrediction"], np.float64)
    rb = np.asarray(q[pf.name]["rawPrediction"], np.float64)
    if prob_tol is not None:
        assert float(np.abs(ra - rb).max()) <= prob_tol, \
            float(np.abs(ra - rb).max())


def _linear_tol(model, sub, bits=8):
    """Stated linear-path bound: |Δ raw| <= sum_d |w_d|·scale_d/2 over
    the batch's own per-feature range, plus bf16 table rounding."""
    pred_stage = [s for s in model.fitted.values()
                  if hasattr(s, "beta") or hasattr(s, "W")][0]
    w = np.abs(np.asarray(getattr(pred_stage, "beta",
                                  getattr(pred_stage, "W", None))))
    X = np.stack([np.asarray(sub.column("x1")),
                  np.asarray(sub.column("x2"))], axis=1)
    span = X.max(0) - X.min(0)
    scale = span / float((1 << bits) - 1)
    wmat = w.reshape(len(scale), -1)
    quant_err = float((wmat * scale[:, None] / 2.0).sum())
    bf16_err = float(np.abs(wmat).sum()) * 2.0 ** -8 * float(
        np.abs(X).max())
    return quant_err + bf16_err + 1e-5


def test_quant_parity_logistic_within_stated_tolerance():
    model, pf, _ = _train(OpLogisticRegression(max_iter=30))
    sub = _score_ds(64)
    f32 = model._ensure_compiled().score_padded(sub, 64)
    q = model._ensure_compiled(quant="int8").score_padded(sub, 64)
    tol = _linear_tol(model, sub)
    ra = np.asarray(f32[pf.name]["rawPrediction"], np.float64)
    rb = np.asarray(q[pf.name]["rawPrediction"], np.float64)
    assert float(np.abs(ra - rb).max()) <= tol, \
        (float(np.abs(ra - rb).max()), tol)


def test_quant_parity_linear_regression():
    model, pf, _ = _train(OpLinearRegression())
    sub = _score_ds(64)
    f32 = model._ensure_compiled().score_padded(sub, 64)
    q = model._ensure_compiled(quant="int8").score_padded(sub, 64)
    tol = _linear_tol(model, sub)
    assert float(np.abs(
        np.asarray(f32[pf.name]["prediction"], np.float64)
        - np.asarray(q[pf.name]["prediction"], np.float64)).max()) <= tol


def test_quant_parity_glm():
    model, pf, _ = _train(
        OpGeneralizedLinearRegression(family="binomial", max_iter=40))
    sub = _score_ds(64)
    f32 = model._ensure_compiled().score_padded(sub, 64)
    q = model._ensure_compiled(quant="int8").score_padded(sub, 64)
    # the GLM raw prediction is the linear eta — the stated linear bound
    tol = _linear_tol(model, sub)
    ra = np.asarray(f32[pf.name]["rawPrediction"], np.float64)
    rb = np.asarray(q[pf.name]["rawPrediction"], np.float64)
    assert float(np.abs(ra - rb).max()) <= tol


def test_quant_parity_forest():
    _parity(OpRandomForestClassifier(n_trees=5, max_depth=3),
            agree_min=0.95)


def test_quant_parity_gbt():
    _parity(OpXGBoostClassifier(n_estimators=5, max_depth=3),
            agree_min=0.95)


def test_quant_int4_parity_is_coarser_but_bounded():
    model, pf, _ = _train(OpLogisticRegression(max_iter=30))
    sub = _score_ds(64)
    f32 = model._ensure_compiled().score_padded(sub, 64)
    q4 = model._ensure_compiled(quant="int4").score_padded(sub, 64)
    tol = _linear_tol(model, sub, bits=4)
    ra = np.asarray(f32[pf.name]["rawPrediction"], np.float64)
    rb = np.asarray(q4[pf.name]["rawPrediction"], np.float64)
    assert float(np.abs(ra - rb).max()) <= tol


def test_narrowed_tree_tables_are_shape_gated():
    model, pf, _ = _train(OpRandomForestClassifier(n_trees=3, max_depth=2))
    forest = [s for s in model.fitted.values()
              if type(s).__name__ == "ForestClassificationModel"][0]
    consts = forest.device_constants()
    narrow = forest.narrow_device_constants(consts)
    assert str(narrow["edges"].dtype) == "float16"
    assert str(narrow["trees"]["feat"].dtype) == "int16"
    assert str(narrow["trees"]["bin"].dtype) == "uint8"
    assert str(narrow["trees"]["leaf"].dtype) == "float32"


# --------------------------------------------------------------------- #
# signatures: quant folding + lifted linear sharing                     #
# --------------------------------------------------------------------- #

def test_signature_folds_quant_config():
    model, _, _ = _train(OpLogisticRegression(max_iter=20))
    s_f32 = scoring_signature(model)
    s_q8 = scoring_signature(model, quant="int8")
    s_q4 = scoring_signature(model, quant=ScoringQuant("int4"))
    assert len({s_f32, s_q8, s_q4}) == 3
    assert scoring_signature(model, quant="int8") == s_q8


def test_fleet_dedup_honesty_quantized_never_adopts_f32(tmp_path):
    """A quantized and an unquantized member over the SAME artifact must
    land in different compile groups (no cross-adoption)."""
    model, _, _ = _train(OpLogisticRegression(max_iter=20))
    path = str(tmp_path / "m")
    model.save(path)
    fleet = FleetService(FleetConfig(
        models={"f32": path,
                "q8": {"path": path, "serving": {"quantize": "int8"}}},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    shared = fleet.pool.report()
    assert len(shared) == 2
    assert all(len(e["members"]) == 1 for e in shared.values())
    fleet.stop()


def test_lifted_linear_tenants_share_one_program(tmp_path):
    """Two DIFFERENT same-shaped linear fits: one signature, the second
    member warms with zero new traces, and its scores are bit-identical
    to a solo load — parameters are per-tenant state, the program is
    fleet state."""
    m_a, _, _ = _train(OpLogisticRegression(max_iter=30), seed=7)
    m_b, pf_b, _ = _train(
        OpLogisticRegression(max_iter=30), seed=7,
        y_from=lambda x1, x2, eps: x1 - 0.5 * x2 + eps)
    assert scoring_signature(m_a) == scoring_signature(m_b)
    dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
    m_a.save(dir_a)
    m_b.save(dir_b)

    rows = [{"x1": 0.3, "x2": -1.2}, {"x1": -0.5, "x2": 0.8}]
    solo = ScoringService.from_path(dir_b, config=ServingConfig(
        max_batch=4, batch_wait_ms=1.0))
    solo.start()
    solo_rows = solo.score(rows).rows()
    solo.stop()

    fleet = FleetService(FleetConfig(
        models={"a": dir_a},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    before = MONITOR.snapshot()
    fleet.add_model("b", dir_b)
    assert MONITOR.delta(before) == {}, "second tenant must trace nothing"
    assert len(fleet.pool.report()) == 1
    fleet.start()
    fleet_rows = fleet.score("b", rows).rows()
    fleet.stop()
    for s_row, f_row in zip(solo_rows, fleet_rows):
        for key, sv in s_row.items():
            if isinstance(sv, dict):
                for kk in sv:
                    assert sv[kk] == f_row[key][kk]


def test_lifted_hot_swap_same_shaped_refit_compiles_nothing(tmp_path):
    """`/reload` of a same-shaped linear refit adopts the resident
    programs: zero new traces — the warm-refit rolling-swap case PR 10
    proved for trees, now closed for the linear families."""
    m_a, _, _ = _train(OpLogisticRegression(max_iter=30), seed=7)
    m_b, _, _ = _train(
        OpLogisticRegression(max_iter=30), seed=7,
        y_from=lambda x1, x2, eps: x1 - 0.7 * x2 + eps)
    dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
    m_a.save(dir_a)
    m_b.save(dir_b)
    fleet = FleetService(FleetConfig(
        models={"m": dir_a},
        serving={"max_batch": 4, "batch_wait_ms": 1.0}))
    fleet.start()
    before = MONITOR.snapshot()
    result = fleet.reload_model("m", dir_b)
    assert result["status"] == "swapped"
    assert MONITOR.delta(before) == {}, \
        "same-shaped refit swap must compile nothing"
    fleet.stop()


# --------------------------------------------------------------------- #
# accounting                                                            #
# --------------------------------------------------------------------- #

def test_goodput_scoring_section_from_device_dispatch_events():
    from transmogrifai_tpu.obs.goodput import build_report
    from transmogrifai_tpu.obs.trace import TRACER

    model, pf, _ = _train(OpLogisticRegression(max_iter=20))
    scorer = model._ensure_compiled()
    sub = _score_ds(4)
    scorer.score_padded(sub, 4)  # warm outside the span
    with TRACER.span("run:test", new_trace=True) as root:
        scorer.score_padded(sub, 4)
        scorer.score_padded(sub, 4)
    report = build_report(root, TRACER.trace_spans(root.trace_id))
    assert report.scoring["dispatches"] == 2
    assert report.scoring["bytes_in"] > 0
    assert report.scoring["bytes_out"] > 0
    assert "scoring" in report.to_json()


def test_serving_params_quantize_roundtrip():
    from transmogrifai_tpu.workflow.params import ServingParams
    sp = ServingParams.from_json({"quantize": "int8", "max_batch": 8})
    assert sp.quantize == "int8"
    assert sp.to_json()["quantize"] == "int8"
    cfg = sp.to_config()
    assert cfg.quantize == "int8"
    assert ServingParams.from_json(sp.to_json()).quantize == "int8"
