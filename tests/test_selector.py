"""ModelSelector / splitters / validators / sweep tests
(reference: ModelSelectorTest, DataBalancerTest, OpCrossValidationTest)."""

import numpy as np
import pytest

import transmogrifai_tpu.types as t
from transmogrifai_tpu.automl import transmogrify
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.features import FeatureBuilder
from transmogrifai_tpu.models import OpLinearRegression, OpLogisticRegression
from transmogrifai_tpu.selector import (
    BinaryClassificationModelSelector, DataBalancer, DataCutter, DataSplitter,
    MultiClassificationModelSelector, OpCrossValidation, OpTrainValidationSplit,
    ParamGridBuilder, RandomParamBuilder, RegressionModelSelector)
from transmogrifai_tpu.workflow import Workflow


def test_param_grid_builder():
    grids = ParamGridBuilder().add("reg_param", [0.1, 0.2]).add("max_iter", [10, 20]).build()
    assert len(grids) == 4
    assert {g["reg_param"] for g in grids} == {0.1, 0.2}
    assert ParamGridBuilder().build() == [{}]


def test_random_param_builder():
    grids = RandomParamBuilder(seed=1).exponential("reg_param", 1e-4, 1e-1) \
        .subset("max_iter", [10, 50]).build(8)
    assert len(grids) == 8
    assert all(1e-4 <= g["reg_param"] <= 1e-1 for g in grids)
    assert all(g["max_iter"] in (10, 50) for g in grids)
    with pytest.raises(ValueError):
        RandomParamBuilder().exponential("x", 0, 1)


def test_cross_validation_masks():
    y = np.arange(100) % 2
    cv = OpCrossValidation(n_folds=4, seed=0)
    folds = cv.splits(y.astype(float))
    assert len(folds) == 4
    total_val = np.zeros(100)
    for tr, va in folds:
        assert np.all(tr + va == 1.0)  # partition
        total_val += va
    np.testing.assert_array_equal(total_val, 1.0)  # each row in exactly 1 fold


def test_stratified_cv():
    y = np.array([0.0] * 90 + [1.0] * 10)
    folds = OpCrossValidation(n_folds=5, stratify=True, seed=0).splits(y)
    for tr, va in folds:
        assert y[va > 0.5].sum() == 2  # exactly 10/5 positives per fold


def test_train_validation_split():
    y = np.zeros(1000)
    folds = OpTrainValidationSplit(train_ratio=0.75, seed=0).splits(y)
    assert len(folds) == 1
    assert folds[0][0].sum() == pytest.approx(750, abs=50)


def test_data_splitter():
    y = np.arange(100, dtype=float)
    tr, te, s = DataSplitter(reserve_test_fraction=0.2, seed=0).split(y)
    assert len(te) == 20 and len(tr) == 80
    assert len(np.intersect1d(tr, te)) == 0
    assert s.n_test == 20


def test_data_balancer():
    y = np.array([1.0] * 20 + [0.0] * 980)
    b = DataBalancer(sample_fraction=0.25, seed=0)
    tr, te, _ = b.split(y)
    prepared, details = b.prepare(y, tr)
    yp = y[prepared]
    frac = yp.sum() / len(yp)
    assert details["balanced"]
    assert frac >= 0.2  # minority lifted to ~sample_fraction


def test_data_cutter():
    y = np.array([0.0] * 50 + [1.0] * 40 + [2.0] * 5 + [3.0] * 5)
    c = DataCutter(max_label_categories=2, reserve_test_fraction=0.0)
    tr, te, _ = c.split(y)
    prepared, details = c.prepare(y, tr)
    assert set(np.unique(y[prepared])) == {0.0, 1.0}
    assert set(details["labels_dropped"]) == {2.0, 3.0}


def _binary_ds(n=300, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = (x1 + 0.5 * x2 + rng.normal(0, 0.5, n) > 0).astype(int)
    return Dataset.from_rows(
        [{"x1": float(x1[i]), "x2": float(x2[i]), "y": int(y[i])} for i in range(n)])


@pytest.mark.slow
def test_binary_selector_end_to_end():
    ds = _binary_ds()
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = transmogrify(preds)
    sel = BinaryClassificationModelSelector.with_cross_validation(
        n_folds=3, splitter=DataSplitter(reserve_test_fraction=0.2))
    pf = sel.set_input(label, vec).get_output()
    model = Workflow().set_result_features(pf, label).set_input_dataset(ds).train()
    fitted = model.fitted[pf.origin_stage.uid]
    s = fitted.summary
    # reference-shaped default grid (BinaryClassificationModelSelector.scala
    # :70-137): LR 8 elastic-net configs + RF 18 + XGB 2 = 28
    assert s.best_model in ("OpLogisticRegression", "OpRandomForestClassifier",
                            "OpXGBoostClassifier")
    assert len(s.validation_results) == 28
    assert all(len(r.fold_metrics) == 3 for r in s.validation_results)
    assert s.holdout_metrics["AuPR"] > 0.7
    assert s.train_metrics["AuROC"] > 0.7
    assert "Evaluated 28 model configs" in s.pretty()


@pytest.mark.slow
def test_multiclass_selector():
    rng = np.random.default_rng(1)
    n = 400
    X = rng.normal(size=(n, 2))
    y = np.argmax(X @ rng.normal(size=(2, 3)) + rng.normal(0, 0.3, (n, 3)), axis=1)
    ds = Dataset.from_rows(
        [{"a": float(X[i, 0]), "b": float(X[i, 1]), "y": int(y[i])} for i in range(n)])
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = transmogrify(preds)
    sel = MultiClassificationModelSelector.with_cross_validation(n_folds=2)
    pf = sel.set_input(label, vec).get_output()
    model = Workflow().set_result_features(pf, label).set_input_dataset(ds).train()
    scores = model.score(ds)
    assert np.asarray(scores[pf.name].data["probability"]).shape == (n, 3)
    fitted = model.fitted[pf.origin_stage.uid]
    assert fitted.summary.problem_type == "multiclass"
    assert fitted.summary.holdout_metrics["F1"] > 0.6


@pytest.mark.slow
def test_regression_selector():
    rng = np.random.default_rng(2)
    n = 300
    x = rng.normal(size=n)
    y = 2.0 * x + 1.0 + rng.normal(0, 0.1, n)
    ds = Dataset.from_rows(
        [{"x": float(x[i]), "y": float(y[i])} for i in range(n)])
    preds, label = FeatureBuilder.from_dataset(ds, response="y", response_type=t.RealNN)
    vec = transmogrify(preds)
    sel = RegressionModelSelector.with_cross_validation(n_folds=3)
    pf = sel.set_input(label, vec).get_output()
    model = Workflow().set_result_features(pf, label).set_input_dataset(ds).train()
    fitted = model.fitted[pf.origin_stage.uid]
    assert fitted.summary.best_model == "OpLinearRegression"
    # RMSE is smaller-is-better: best grid should win with low error
    assert fitted.summary.holdout_metrics["RMSE"] < 0.5


def test_selector_fault_tolerance():
    ds = _binary_ds(100)
    preds, label = FeatureBuilder.from_dataset(ds, response="y")
    vec = transmogrify(preds)

    from transmogrifai_tpu.models.base import PredictorEstimator

    class ExplodingModel(PredictorEstimator):  # generic sweep path
        def fit_arrays(self, X, y, w, ctx):
            raise RuntimeError("boom")

    from transmogrifai_tpu.selector import ModelSelector

    sel = ModelSelector(
        models=[(ExplodingModel(), [{"reg_param": 0.1}]),
                (OpLogisticRegression(max_iter=20), [{"reg_param": 0.1}])],
        splitter=None)
    pf = sel.set_input(label, vec).get_output()
    model = Workflow().set_result_features(pf).set_input_dataset(ds).train()
    fitted = model.fitted[pf.origin_stage.uid]
    assert fitted.summary.best_model == "OpLogisticRegression"
