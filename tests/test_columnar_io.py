"""Columnar ingestion + vectorized host encode + streaming overlap
(VERDICT r1 #5 / weak#5).
"""

import numpy as np
import pytest

import transmogrifai_tpu.types as T
from transmogrifai_tpu.data import Dataset
from transmogrifai_tpu.ops.categorical import pivot_encode_ids
from transmogrifai_tpu.ops.text import TokenHasher, _hash_counts, tokenize


class TestParquetRoundTrip:
    def test_round_trip_types_and_nulls(self, tmp_path, rng):
        n = 50
        vals = rng.normal(size=n)
        vals[::7] = np.nan
        cats = np.array([None if i % 5 == 0 else f"c{i % 3}" for i in range(n)],
                        dtype=object)
        ds = Dataset({"x": vals, "cat": cats,
                      "k": np.arange(n).astype(np.float64)},
                     {"x": T.Real, "cat": T.PickList, "k": T.Integral})
        p = str(tmp_path / "d.parquet")
        ds.to_parquet(p)
        back = Dataset.from_parquet(p, schema={"cat": T.PickList})
        assert back.schema["x"] is T.Real
        assert back.schema["k"] is T.Integral
        np.testing.assert_allclose(back.column("x"), vals)
        assert list(back.column("cat")) == list(cats)

    def test_arrow_type_inference(self):
        import pyarrow as pa
        t = pa.table({
            "i": pa.array([1, 2, None]),
            "f": pa.array([1.5, None, 2.5]),
            "b": pa.array([True, False, None]),
            "s": pa.array(["a", None, "c"]),
            "ls": pa.array([["x", "y"], None, ["z"]]),
        })
        ds = Dataset.from_arrow(t)
        assert ds.schema["i"] is T.Integral
        assert ds.schema["f"] is T.Real
        assert ds.schema["b"] is T.Binary
        assert ds.schema["s"] is T.Text
        assert ds.schema["ls"] is T.TextList
        assert np.isnan(ds.column("i")[2])
        assert ds.column("s")[1] is None

    def test_no_row_materialization_for_numeric(self, tmp_path, rng):
        n = 1000
        ds = Dataset({"x": rng.normal(size=n)}, {"x": T.Real})
        p = str(tmp_path / "n.parquet")
        ds.to_parquet(p)
        back = Dataset.from_parquet(p)
        assert back.column("x").dtype == np.float64  # typed storage, not object


class TestVectorizedEncode:
    def _naive_hash(self, values, hasher, binary, pre_tok):
        out = np.zeros((len(values), hasher.num_features), dtype=np.float32)
        for i, v in enumerate(values):
            if v is None:
                continue
            for tok in (v if pre_tok else tokenize(v)):
                j = hasher(tok)
                if binary:
                    out[i, j] = 1.0
                else:
                    out[i, j] += 1.0
        return out

    @pytest.mark.parametrize("binary", [False, True])
    def test_hash_counts_match_naive(self, rng, binary):
        words = ["alpha", "beta", "gamma", "delta", "alpha beta",
                 "beta beta gamma", None, ""]
        values = [words[i] for i in rng.integers(len(words), size=200)]
        got = _hash_counts(values, TokenHasher(32), binary, False)
        want = self._naive_hash(values, TokenHasher(32), binary, False)
        np.testing.assert_array_equal(got, want)

    def test_hash_counts_pre_tokenized(self, rng):
        values = [["a", "b", "a"], None, ["c"], []]
        got = _hash_counts(values, TokenHasher(16), False, True)
        want = self._naive_hash(values, TokenHasher(16), False, True)
        np.testing.assert_array_equal(got, want)

    def test_pivot_encode_ids_match_naive(self, rng):
        lut = {"a": 0, "b": 1, "c": 2}
        values = [None, "a", "b", "zz", "c", "a", None, "q"]
        got = pivot_encode_ids(values, lut, 3)
        want = np.asarray([4, 0, 1, 3, 2, 0, 4, 3], dtype=np.int32)
        np.testing.assert_array_equal(got, want)


class TestStreamingScore:
    def test_stream_matches_batch(self, tmp_path, rng):
        import __graft_entry__ as ge
        from transmogrifai_tpu.readers import DataReaders

        model, ds, pf = ge._fit_flagship(n=200)
        # write scoring data to parquet, stream it back in batches
        p = str(tmp_path / "score.parquet")
        ds.to_parquet(p)
        reader = DataReaders.stream(parquet_path=p, batch_size=64,
                                    schema=dict(ds.schema))
        batch_pred = np.asarray(
            model.score_compiled(ds)[pf.name]["prediction"])
        streamed = []
        for out in model.score_stream(reader.stream()):
            streamed.append(np.asarray(out[pf.name]["prediction"]))
        np.testing.assert_array_equal(np.concatenate(streamed), batch_pred)


class TestUnicodeTokenParity:
    def test_arrow_tokens_match_python_on_unicode(self):
        from transmogrifai_tpu.ops.text import tokenize, tokenize_batch
        values = ["café naïve", "日本語 テスト", "a_b-c d", None, "",
                  "Üben ölçü", "hello world"]
        got = tokenize_batch(values)
        for v, g in zip(values, got):
            want = tokenize(v) or None
            assert g == want, (v, g, want)

    def test_hash_counts_unicode_match_naive(self):
        from transmogrifai_tpu.ops.text import TokenHasher, _hash_counts, tokenize
        values = ["café naïve café", "日本語", "Üben", None]
        got = _hash_counts(values, TokenHasher(32), False, False)
        want = np.zeros_like(got)
        h = TokenHasher(32)
        for i, v in enumerate(values):
            for tok in tokenize(v or ""):
                want[i, h(tok)] += 1.0
        np.testing.assert_array_equal(got, want)

    def test_arrow_date_columns(self):
        import datetime
        import pyarrow as pa
        t_ = pa.table({"d": pa.array([datetime.date(2020, 1, 2), None]),
                       "ts": pa.array([datetime.datetime(2021, 3, 4, 5), None])})
        ds = Dataset.from_arrow(t_)
        assert ds.schema["d"] is T.DateTime
        assert ds.column("d")[0] == 1577923200000.0  # 2020-01-02 ms epoch
        assert np.isnan(ds.column("d")[1])

    def test_arrow_float_list_is_geolocation(self):
        import pyarrow as pa
        t_ = pa.table({"g": pa.array([[37.7, -122.4, 1.0], None])})
        assert Dataset.from_arrow(t_).schema["g"] is T.Geolocation


class TestNativeHashKernel:
    def test_native_matches_python_murmur(self, rng):
        from transmogrifai_tpu.native import get_murmur3
        if get_murmur3() is None:
            pytest.skip("no C toolchain")
        from transmogrifai_tpu.ops.text import (
            TokenHasher, _hash_counts, tokenize)
        words = ["alpha", "beta", "gamma", "日本語", "café", None, "",
                 "one two three two"]
        values = [words[i] for i in rng.integers(len(words), size=500)]
        got = _hash_counts(values, TokenHasher(64, seed=7), False, False)
        want = np.zeros_like(got)
        h = TokenHasher(64, seed=7)
        for i, v in enumerate(values):
            for tok in tokenize(v or ""):
                want[i, h(tok)] += 1.0
        np.testing.assert_array_equal(got, want)

    def test_binary_mode(self, rng):
        from transmogrifai_tpu.ops.text import TokenHasher, _hash_counts
        values = ["dup dup dup", "solo", None] * 20
        got = _hash_counts(values, TokenHasher(16), True, False)
        assert got.max() == 1.0


class TestNativeCsvParser:
    """One-pass C numeric CSV kernel (native/csv_parse.c) vs python path."""

    def _write(self, tmp_path, text, name="n.csv"):
        p = tmp_path / name
        p.write_text(text)
        return str(p)

    def test_numeric_fast_path_matches_python(self, tmp_path):
        import io as _io
        rng = np.random.default_rng(0)
        n = 500
        lines = ["a,b,c,d"]
        for i in range(n):
            cells = [f"{rng.normal():.6f}", str(int(rng.integers(100))),
                     "" if i % 7 == 0 else f"{rng.normal():.3f}",
                     "NA" if i % 11 == 0 else str(i)]
            lines.append(",".join(cells))
        text = "\n".join(lines) + "\n"
        path = self._write(tmp_path, text)
        ds_fast = Dataset.from_csv(path)
        ds_py = Dataset.from_csv(_io.StringIO(text))
        assert ds_fast.n_rows == ds_py.n_rows == n
        for col in ("a", "b", "c", "d"):
            f, p = ds_fast.column(col), ds_py.column(col)
            assert f.dtype == np.float64
            np.testing.assert_allclose(
                np.where(np.isnan(f), -9e9, f),
                np.where(np.isnan(np.asarray(p, float)), -9e9,
                         np.asarray(p, float)))
        assert ds_fast.schema["b"] is T.Integral
        assert ds_fast.schema["a"] is T.Real

    def test_mixed_text_falls_back(self, tmp_path):
        path = self._write(tmp_path, "x,s\n1.5,hello\n2.5,world\n")
        ds = Dataset.from_csv(path)
        assert list(ds.column("s")) == ["hello", "world"]
        assert ds.schema["s"] is T.Text

    def test_quoted_and_crlf(self, tmp_path):
        path = self._write(tmp_path, 'x,y\r\n"1.5",2\r\n"3.25",4\r\n')
        ds = Dataset.from_csv(path)
        np.testing.assert_allclose(ds.column("x"), [1.5, 3.25])
        np.testing.assert_allclose(ds.column("y"), [2.0, 4.0])

    def test_short_rows_and_no_trailing_newline(self, tmp_path):
        path = self._write(tmp_path, "x,y\n1,2\n3\n5,6")
        ds = Dataset.from_csv(path)
        assert ds.n_rows == 3
        np.testing.assert_allclose(ds.column("x"), [1.0, 3.0, 5.0])
        y = ds.column("y")
        assert y[0] == 2.0 and np.isnan(y[1]) and y[2] == 6.0

    def test_native_kernel_is_used(self, tmp_path):
        from transmogrifai_tpu.native import get_csv_parser
        if get_csv_parser() is None:
            pytest.skip("no C toolchain in image")
        path = self._write(tmp_path, "x\n1\n2\n")
        ds = Dataset._from_csv_native(path, None, ",")
        assert ds is not None and ds.n_rows == 2

    def test_late_text_and_bigint_fall_back(self, tmp_path):
        # text appears after the inference sample -> python path must win
        lines = ["v"] + [str(i) for i in range(2500)] + ["ERROR", "7"]
        p1 = self._write(tmp_path, "\n".join(lines) + "\n", "late.csv")
        ds = Dataset.from_csv(p1)
        col = ds.column("v")
        assert col[2500] == "ERROR"  # preserved, not NaN'd

        # big exact ints keep object storage (join keys must not round)
        big = 9007199254740993
        p2 = self._write(tmp_path, f"id\n{big}\n{big + 2}\n", "big.csv")
        ds2 = Dataset.from_csv(p2)
        assert ds2.column("id")[0] == big  # exact

    def test_trailing_blank_line_matches_python(self, tmp_path):
        import io as _io
        text = "x,y\n1,2\n\n"
        p = self._write(tmp_path, text, "blank.csv")
        ds_fast = Dataset.from_csv(p)
        ds_py = Dataset.from_csv(_io.StringIO(text))
        assert ds_fast.n_rows == ds_py.n_rows

    def test_bare_cr_row_breaks(self, tmp_path):
        import io as _io
        # old-Mac \r row separators past any sample: python csv splits on
        # them and so must the C kernel
        text = "x,y\n1,2\r3,4\r\n5,6\n"
        p = self._write(tmp_path, text, "cr.csv")
        ds_fast = Dataset.from_csv(p)
        ds_py = Dataset.from_csv(_io.StringIO(text, newline=""))
        assert ds_fast.n_rows == ds_py.n_rows == 3
        np.testing.assert_allclose(ds_fast.column("x"), [1.0, 3.0, 5.0])
        np.testing.assert_allclose(ds_fast.column("y"), [2.0, 4.0, 6.0])

    def test_quoted_junk_falls_back(self, tmp_path):
        import io as _io
        text = 'x,y\n"1.5"x,9\n2,3\n'
        p = self._write(tmp_path, text, "junk.csv")
        ds_fast = Dataset.from_csv(p)
        ds_py = Dataset.from_csv(_io.StringIO(text, newline=""))
        # fast path must defer (python concatenates '1.5x' -> Text column)
        assert list(ds_fast.column("x")) == list(ds_py.column("x"))
        assert ds_py.column("x")[0] == "1.5x"

    def test_int_then_float_widens_to_real(self, tmp_path):
        lines = ["v"] + [str(i) for i in range(2500)] + ["2.5"]
        p = self._write(tmp_path, "\n".join(lines) + "\n", "widen.csv")
        ds = Dataset.from_csv(p)
        assert ds.schema["v"] is T.Real
        assert ds.column("v")[2500] == 2.5


class TestGroupedFetchStreaming:
    def test_grouped_fetch_matches_per_batch(self, tmp_path, rng):
        """fetch_group packs K batches' results into one device buffer +
        one materialization; outputs must match per-batch fetching."""
        import __graft_entry__ as ge
        from transmogrifai_tpu.readers import DataReaders

        model, ds, pf = ge._fit_flagship(n=200)
        p = str(tmp_path / "score.parquet")
        ds.to_parquet(p)
        reader = DataReaders.stream(parquet_path=p, batch_size=32,
                                    schema=dict(ds.schema))
        base = [np.asarray(o[pf.name]["prediction"])
                for o in model.score_stream(reader.stream())]
        grouped = [np.asarray(o[pf.name]["prediction"])
                   for o in model.score_stream(reader.stream(),
                                               fetch_group=3)]
        assert len(base) == len(grouped)
        np.testing.assert_array_equal(np.concatenate(base),
                                      np.concatenate(grouped))
        probs = [o[pf.name]["probability"]
                 for o in model.score_stream(reader.stream(),
                                             fetch_group=3)]
        assert all(isinstance(pb, np.ndarray) for pb in probs)

    def test_coalesced_batches_match_per_batch(self, tmp_path, rng):
        """coalesce_rows merges micro-batches into bigger dispatches but
        must preserve the one-result-per-input-batch contract, batch
        boundaries, and values — incl. a trailing partial super-batch."""
        import __graft_entry__ as ge
        from transmogrifai_tpu.readers import DataReaders

        model, ds, pf = ge._fit_flagship(n=200)
        p = str(tmp_path / "score.parquet")
        ds.to_parquet(p)
        # 200 rows / 32-row batches = 7 batches (last short); coalescing
        # to >=96 rows gives super-batches of 96, 96, 8 — a partial tail
        reader = DataReaders.stream(parquet_path=p, batch_size=32,
                                    schema=dict(ds.schema))
        base = [np.asarray(o[pf.name]["prediction"])
                for o in model.score_stream(reader.stream())]
        coal = [np.asarray(o[pf.name]["prediction"])
                for o in model.score_stream(reader.stream(),
                                            coalesce_rows=96,
                                            fetch_group=2)]
        assert [len(b) for b in base] == [len(c) for c in coal]
        np.testing.assert_array_equal(np.concatenate(base),
                                      np.concatenate(coal))
