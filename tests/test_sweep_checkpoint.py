"""Sweep checkpoint/resume (SURVEY.md §5.4 — long-sweep resumability)."""

import glob
import os

import numpy as np
import pytest

import transmogrifai_tpu.selector.model_selector as ms_mod
from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
from transmogrifai_tpu.models import OpLogisticRegression, OpNaiveBayes
from transmogrifai_tpu.selector import ModelSelector, DataSplitter
from transmogrifai_tpu.selector.validators import OpCrossValidation
from transmogrifai_tpu.stages.base import FitContext
from transmogrifai_tpu.data.columns import Column
import transmogrifai_tpu.types as T


def _cols(n=200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.normal(0, 0.4, n) > 0).astype(np.float64)
    label = Column(T.RealNN, {"value": y, "mask": np.ones(n, bool)})
    vec = Column(T.OPVector, X.astype(np.float32))
    return label, vec


def _selector(tmp, models=None):
    return ModelSelector(
        models=models or [
            (OpLogisticRegression(max_iter=10),
             [{"reg_param": 0.01}, {"reg_param": 0.1}]),
            (OpLogisticRegression(max_iter=5), [{"reg_param": 0.05}]),
        ],
        validator=OpCrossValidation(n_folds=2),
        splitter=DataSplitter(reserve_test_fraction=0.2),
        evaluator=BinaryClassificationEvaluator(),
        checkpoint_dir=str(tmp))


@pytest.mark.slow
def test_checkpoint_files_written_and_reused(tmp_path, monkeypatch):
    label, vec = _cols()
    sel = _selector(tmp_path)
    m1 = sel.fit_model([label, vec], FitContext(n_rows=200, seed=7))
    files = sorted(glob.glob(str(tmp_path / "sweep_*.json")))
    assert len(files) == 2, files

    calls = {"n": 0}
    real = ms_mod.run_sweep

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(ms_mod, "run_sweep", counting)
    sel2 = _selector(tmp_path)
    m2 = sel2.fit_model([label, vec], FitContext(n_rows=200, seed=7))
    assert calls["n"] == 0, "resume should not re-run any family sweep"
    s1, s2 = m1.summary, m2.summary
    assert [r.fold_metrics for r in s1.validation_results] == \
        [r.fold_metrics for r in s2.validation_results]
    assert s1.best_grid == s2.best_grid


@pytest.mark.slow
def test_partial_resume_runs_only_missing_family(tmp_path, monkeypatch):
    label, vec = _cols()
    sel = _selector(tmp_path)
    sel.fit_model([label, vec], FitContext(n_rows=200, seed=7))
    files = sorted(glob.glob(str(tmp_path / "sweep_*.json")))
    os.remove(files[0])  # simulate dying before family 0 persisted

    calls = {"n": 0}
    real = ms_mod.run_sweep

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(ms_mod, "run_sweep", counting)
    _selector(tmp_path).fit_model([label, vec], FitContext(n_rows=200, seed=7))
    assert calls["n"] == 1


@pytest.mark.slow
def test_signature_invalidates_on_different_data_or_grids(tmp_path):
    label, vec = _cols()
    sel = _selector(tmp_path)
    sel.fit_model([label, vec], FitContext(n_rows=200, seed=7))
    n_before = len(glob.glob(str(tmp_path / "sweep_*.json")))

    # different seed → different signature → new checkpoint files
    sel2 = _selector(tmp_path)
    sel2.fit_model([label, vec], FitContext(n_rows=200, seed=8))
    n_after = len(glob.glob(str(tmp_path / "sweep_*.json")))
    assert n_after == 2 * n_before

    # corrupted checkpoint falls back to re-running, not crashing
    files = sorted(glob.glob(str(tmp_path / "sweep_*.json")))
    with open(files[0], "w") as f:
        f.write("{not json")
    sel3 = _selector(tmp_path)
    m3 = sel3.fit_model([label, vec], FitContext(n_rows=200, seed=7))
    assert np.isfinite(
        [r.mean_metric for r in m3.summary.validation_results]).all()


def test_no_checkpoint_dir_is_default_noop(tmp_path):
    label, vec = _cols()
    sel = ModelSelector(
        models=[(OpLogisticRegression(max_iter=5), [{"reg_param": 0.1}])],
        validator=OpCrossValidation(n_folds=2),
        evaluator=BinaryClassificationEvaluator())
    sel.fit_model([label, vec], FitContext(n_rows=200, seed=7))
    assert not glob.glob(str(tmp_path / "sweep_*.json"))
